"""Optimizers: minimize = append_backward + regularization/clip + per-param
optimizer ops (reference python/paddle/fluid/optimizer.py:294
Optimizer.minimize, :197 _create_optimization_pass).

Optimizer state (moments, accumulators) are persistable variables initialized
in the startup program; the update ops write ParamOut/MomentOut under the SAME
variable names, which the executor turns into donated in-place buffer updates
on TPU (executor.py).
"""

import contextlib

import numpy as np

from . import framework
from .backward import append_backward
from .clip import append_gradient_clip_ops, error_clip_callback
from .framework import OpRole, Variable, default_main_program, default_startup_program
from .initializer import Constant
from .layer_helper import LayerHelper
from .regularizer import append_regularization_ops
from . import unique_name

__all__ = [
    "SGD",
    "Momentum",
    "Adagrad",
    "Adam",
    "Adamax",
    "DecayedAdagrad",
    "Ftrl",
    "SGDOptimizer",
    "MomentumOptimizer",
    "AdagradOptimizer",
    "AdamOptimizer",
    "AdamaxOptimizer",
    "DecayedAdagradOptimizer",
    "RMSPropOptimizer",
    "FtrlOptimizer",
    "AdadeltaOptimizer",
    "ModelAverage",
    "ProximalGD",
    "ProximalAdagrad",
    "ProximalGDOptimizer",
    "ProximalAdagradOptimizer",
    "LarsMomentum",
    "LarsMomentumOptimizer",
]


def _is_selected_rows(grad):
    """True when backward emitted this grad as a SelectedRows pair
    (embedding/selected_rows.py — is_sparse=True lookup tables)."""
    return bool(getattr(grad, "is_selected_rows", False))


def _param_shard_axis(param):
    """Mesh axis the param is row-sharded over ('' when unsharded) — forwarded
    to the sparse update op so it shard_maps the scatter per-rank. Reads the
    legacy per-var attr first, then the program's declarative sharding rules
    (parallel.sharding_rules — where the embedding engine registers its
    `ep` layout)."""
    spec = getattr(param, "sharding_spec", None)
    if not spec:
        rules = getattr(param.block.program, "_sharding_rules", None)
        if rules is not None:
            spec = rules.match(param.name)
    if spec:
        first = spec[0]
        if isinstance(first, (tuple, list)):
            first = first[0] if first else None
        if isinstance(first, str):
            return first
    return ""


def _sparse_grad_io(param, grad):
    """Extra inputs/attrs every *_sparse optimizer op takes."""
    inputs = {"GradRows": [grad.selected_rows_rows]}
    attrs = {
        "axis_name": _param_shard_axis(param),
        "param": param.name,
    }
    return inputs, attrs


def _densify_grad(block, param, grad):
    """SelectedRows → dense (rows, dim) grad for optimizers without a sparse
    kernel. Keeps correctness, loses the O(touched-rows) cost."""
    dense = block.create_var(
        name=unique_name.generate(grad.name + "@DENSE"),
        shape=param.shape,
        dtype=grad.dtype,
        persistable=False,
    )
    block.append_op(
        type="selected_rows_to_dense",
        inputs={"X": [grad.name], "Rows": [grad.selected_rows_rows]},
        outputs={"Out": [dense.name]},
        attrs={"height": int(param.shape[0])},
    )
    return dense


class Optimizer:
    def __init__(self, learning_rate, regularization=None, name=None):
        if not isinstance(learning_rate, (float, Variable)):
            raise TypeError("learning_rate must be float or Variable")
        self._name = name
        self.regularization = regularization
        self._learning_rate = learning_rate
        self._learning_rate_map = {}
        self._accumulators = {}  # accum name -> {param name -> var}
        self.helper = None

    # --- learning rate plumbing (reference optimizer.py:87-146) ---
    def _create_global_learning_rate(self):
        program = default_main_program()
        lr = self._learning_rate_map.get(program)
        if lr is not None:
            return
        if isinstance(self._learning_rate, Variable):
            self._learning_rate_map[program] = self._learning_rate
            return
        lr_name = unique_name.generate("learning_rate")
        lr_var = program.global_block().create_var(
            name=lr_name, shape=[1], dtype="float32", persistable=True
        )
        lr_var.stop_gradient = True
        self._learning_rate_map[program] = lr_var
        startup = default_startup_program().global_block()
        sv = startup.create_var(
            name=lr_name, shape=[1], dtype="float32", persistable=True
        )
        Constant(float(self._learning_rate))(sv, startup)

    def _global_learning_rate(self, program=None):
        program = program or default_main_program()
        return self._learning_rate_map.get(program)

    def _create_param_lr(self, param_and_grad):
        param = param_and_grad[0]
        param_lr = param.optimize_attr.get("learning_rate", 1.0)
        base = self._global_learning_rate()
        if float(param_lr) == 1.0:
            return base
        from .layers import tensor as tensor_layers

        with default_main_program()._lr_schedule_guard():
            return tensor_layers.scale(base, scale=float(param_lr))

    # --- accumulators (reference optimizer.py:148-196) ---
    def _add_accumulator(
        self, name, param, dtype=None, fill_value=0.0, shape=None
    ):
        if name in self._accumulators and param.name in self._accumulators[name]:
            return self._accumulators[name][param.name]
        if shape is None:
            shape = list(param.shape)
        dtype = dtype or param.dtype
        var_name = unique_name.generate("%s_%s_%s" % (param.name, name, "acc"))
        block = default_main_program().global_block()
        var = block.create_var(
            name=var_name, shape=shape, dtype=dtype, persistable=True
        )
        var.stop_gradient = True
        # same-shape accumulators inherit the param's mesh placement: a
        # row-sharded embedding table (sharding_spec=("ep", None)) gets its
        # moments row-sharded alongside it — the ZeRO-along-ep composition
        # (executor.state_sharding reads this spec); scalar accumulators
        # (beta pows, shape [1]) stay replicated
        spec = getattr(param, "sharding_spec", None)
        if spec is not None and list(shape) == list(param.shape):
            var.sharding_spec = tuple(spec)
        startup = default_startup_program().global_block()
        sv = startup.create_var(
            name=var_name, shape=shape, dtype=dtype, persistable=True
        )
        Constant(float(fill_value))(sv, startup)
        self._accumulators.setdefault(name, {})[param.name] = var
        return var

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    # --- hooks ---
    def _create_accumulators(self, block, parameters):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    def _finish_update(self, block, parameters_and_grads):
        pass

    def _create_optimization_pass(self, parameters_and_grads):
        from .ops.sparse_ops import SPARSE_OPTIMIZER_TYPES

        program = default_main_program()
        block = program.global_block()
        self.helper = LayerHelper(self.__class__.__name__)
        self._create_global_learning_rate()
        self._create_accumulators(
            block, [p for p, g in parameters_and_grads if g is not None]
        )
        optimize_ops = []
        for param_and_grad in parameters_and_grads:
            if param_and_grad[1] is None:
                continue
            if _is_selected_rows(param_and_grad[1]) and (
                getattr(self, "type", None) not in SPARSE_OPTIMIZER_TYPES
            ):
                # no per-row kernel for this optimizer: densify the
                # SelectedRows pair first (reference merges SelectedRows to
                # LoDTensor before a dense apply the same way)
                with program._optimized_guard(param_and_grad):
                    param_and_grad = (
                        param_and_grad[0],
                        _densify_grad(block, *param_and_grad),
                    )
            with program._optimized_guard(param_and_grad):
                op = self._append_optimize_op(block, param_and_grad)
                optimize_ops.append(op)
        self._finish_update(block, parameters_and_grads)
        return optimize_ops

    def backward(self, loss, startup_program=None, parameter_list=None, no_grad_set=None, callbacks=None):
        return append_backward(loss, parameter_list, no_grad_set, callbacks or [error_clip_callback])

    def apply_gradients(self, params_grads):
        params_grads = sorted(params_grads, key=lambda x: x[0].name)
        params_grads = append_gradient_clip_ops(params_grads)
        params_grads = append_regularization_ops(params_grads, self.regularization)
        return self._create_optimization_pass(params_grads)

    def minimize(
        self, loss, startup_program=None, parameter_list=None, no_grad_set=None
    ):
        params_grads = self.backward(
            loss, startup_program, parameter_list, no_grad_set
        )
        optimize_ops = self.apply_gradients(params_grads)
        return optimize_ops, params_grads


class SGDOptimizer(Optimizer):
    """reference optimizer.py SGDOptimizer → optimizers/sgd_op.cc"""

    def __init__(self, learning_rate, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "sgd"

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        inputs = {
            "Param": [p.name],
            "Grad": [g.name],
            "LearningRate": [self._create_param_lr(param_and_grad).name],
        }
        if _is_selected_rows(g):
            sp_in, sp_attrs = _sparse_grad_io(p, g)
            inputs.update(sp_in)
            return block.append_op(
                type="sgd_sparse",
                inputs=inputs,
                outputs={"ParamOut": [p.name]},
                attrs=sp_attrs,
            )
        return block.append_op(
            type="sgd",
            inputs=inputs,
            outputs={"ParamOut": [p.name]},
        )


class MomentumOptimizer(Optimizer):
    """reference optimizer.py MomentumOptimizer → optimizers/momentum_op.cc"""

    _velocity_acc_str = "velocity"

    def __init__(self, learning_rate, momentum, use_nesterov=False, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "momentum"
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._velocity_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        velocity = self._get_accumulator(self._velocity_acc_str, p)
        return block.append_op(
            type="momentum",
            inputs={
                "Param": [p.name],
                "Grad": [g.name],
                "Velocity": [velocity.name],
                "LearningRate": [self._create_param_lr(param_and_grad).name],
            },
            outputs={"ParamOut": [p.name], "VelocityOut": [velocity.name]},
            attrs={"mu": self._momentum, "use_nesterov": self._use_nesterov},
        )


class LarsMomentumOptimizer(MomentumOptimizer):
    """reference optimizer.py LarsMomentumOptimizer → lars_momentum_op.cc"""

    def __init__(
        self,
        learning_rate,
        momentum,
        lars_coeff=0.001,
        lars_weight_decay=0.0005,
        **kwargs,
    ):
        super().__init__(learning_rate, momentum, **kwargs)
        self.type = "lars_momentum"
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        velocity = self._get_accumulator(self._velocity_acc_str, p)
        return block.append_op(
            type="lars_momentum",
            inputs={
                "Param": [p.name],
                "Grad": [g.name],
                "Velocity": [velocity.name],
                "LearningRate": [self._create_param_lr(param_and_grad).name],
            },
            outputs={"ParamOut": [p.name], "VelocityOut": [velocity.name]},
            attrs={
                "mu": self._momentum,
                "lars_coeff": self._lars_coeff,
                "lars_weight_decay": self._lars_weight_decay,
            },
        )


class AdagradOptimizer(Optimizer):
    _moment_acc_str = "moment"

    def __init__(self, learning_rate, epsilon=1e-6, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "adagrad"
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        moment = self._get_accumulator(self._moment_acc_str, p)
        inputs = {
            "Param": [p.name],
            "Grad": [g.name],
            "Moment": [moment.name],
            "LearningRate": [self._create_param_lr(param_and_grad).name],
        }
        attrs = {"epsilon": self._epsilon}
        op_type = "adagrad"
        if _is_selected_rows(g):
            sp_in, sp_attrs = _sparse_grad_io(p, g)
            inputs.update(sp_in)
            attrs.update(sp_attrs)
            op_type = "adagrad_sparse"
        return block.append_op(
            type=op_type,
            inputs=inputs,
            outputs={"ParamOut": [p.name], "MomentOut": [moment.name]},
            attrs=attrs,
        )


class AdamOptimizer(Optimizer):
    _moment1_acc_str = "moment1"
    _moment2_acc_str = "moment2"
    _beta1_pow_acc_str = "beta1_pow_acc"
    _beta2_pow_acc_str = "beta2_pow_acc"

    def __init__(
        self,
        learning_rate=0.001,
        beta1=0.9,
        beta2=0.999,
        epsilon=1e-8,
        lazy_mode=False,
        moment_dtype=None,
        **kwargs,
    ):
        """moment_dtype="bfloat16" stores BOTH moments in bf16 (beyond the
        reference — the 8-bit-Adam family technique, TPU-style): halves
        optimizer-state memory and its HBM traffic in the fused dW+update
        tier (the round-4 per-HLO audit measured that traffic at ~0.56 ms
        per large dW fusion, PROFILE.md). The update itself still computes
        in f32 (ops/core_ops.py _opt_f32 upcasts state and casts the
        written-back moments to their storage dtype); bias-correction pows
        stay f32. bf16 keeps f32's exponent range, so unlike int8 quantized
        moments no blockwise rescaling is needed; the cost is ~8-bit
        mantissa noise on m/v — convergence-tested in
        tests/test_ops_optimizers.py."""
        super().__init__(learning_rate, **kwargs)
        self.type = "adam"
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._moment_dtype = moment_dtype

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(
                self._moment1_acc_str, p, dtype=self._moment_dtype
            )
            self._add_accumulator(
                self._moment2_acc_str, p, dtype=self._moment_dtype
            )
            self._add_accumulator(
                self._beta1_pow_acc_str, p, fill_value=self._beta1, shape=[1]
            )
            self._add_accumulator(
                self._beta2_pow_acc_str, p, fill_value=self._beta2, shape=[1]
            )

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m1 = self._get_accumulator(self._moment1_acc_str, p)
        m2 = self._get_accumulator(self._moment2_acc_str, p)
        b1p = self._get_accumulator(self._beta1_pow_acc_str, p)
        b2p = self._get_accumulator(self._beta2_pow_acc_str, p)
        inputs = {
            "Param": [p.name],
            "Grad": [g.name],
            "LearningRate": [self._create_param_lr(param_and_grad).name],
            "Moment1": [m1.name],
            "Moment2": [m2.name],
            "Beta1Pow": [b1p.name],
            "Beta2Pow": [b2p.name],
        }
        attrs = {
            "beta1": self._beta1,
            "beta2": self._beta2,
            "epsilon": self._epsilon,
        }
        op_type = "adam"
        if _is_selected_rows(g):
            # lazy Adam (reference adam_op SparseAdamFunctor lazy_mode):
            # untouched rows' params AND moments stay frozen this step
            sp_in, sp_attrs = _sparse_grad_io(p, g)
            inputs.update(sp_in)
            attrs.update(sp_attrs)
            op_type = "adam_sparse"
        return block.append_op(
            type=op_type,
            inputs=inputs,
            outputs={
                "ParamOut": [p.name],
                "Moment1Out": [m1.name],
                "Moment2Out": [m2.name],
            },
            attrs=attrs,
        )

    def _finish_update(self, block, parameters_and_grads):
        """Advance beta^t accumulators with scale ops (reference
        optimizer.py AdamOptimizer._finish_update)."""
        program = default_main_program()
        for p, g in parameters_and_grads:
            if g is None:
                continue
            with program._optimized_guard([p, g]):
                b1p = self._get_accumulator(self._beta1_pow_acc_str, p)
                b2p = self._get_accumulator(self._beta2_pow_acc_str, p)
                block.append_op(
                    type="scale",
                    inputs={"X": [b1p.name]},
                    outputs={"Out": [b1p.name]},
                    attrs={"scale": self._beta1},
                )
                block.append_op(
                    type="scale",
                    inputs={"X": [b2p.name]},
                    outputs={"Out": [b2p.name]},
                    attrs={"scale": self._beta2},
                )


class AdamaxOptimizer(Optimizer):
    _moment_acc_str = "moment"
    _inf_norm_acc_str = "inf_norm"
    _beta1_pow_acc_str = "beta1_pow_acc"

    def __init__(
        self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, **kwargs
    ):
        super().__init__(learning_rate, **kwargs)
        self.type = "adamax"
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)
            self._add_accumulator(self._inf_norm_acc_str, p)
            self._add_accumulator(
                self._beta1_pow_acc_str, p, fill_value=self._beta1, shape=[1]
            )

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        moment = self._get_accumulator(self._moment_acc_str, p)
        inf_norm = self._get_accumulator(self._inf_norm_acc_str, p)
        b1p = self._get_accumulator(self._beta1_pow_acc_str, p)
        return block.append_op(
            type="adamax",
            inputs={
                "Param": [p.name],
                "Grad": [g.name],
                "LearningRate": [self._create_param_lr(param_and_grad).name],
                "Moment": [moment.name],
                "InfNorm": [inf_norm.name],
                "Beta1Pow": [b1p.name],
            },
            outputs={
                "ParamOut": [p.name],
                "MomentOut": [moment.name],
                "InfNormOut": [inf_norm.name],
            },
            attrs={
                "beta1": self._beta1,
                "beta2": self._beta2,
                "epsilon": self._epsilon,
            },
        )

    def _finish_update(self, block, parameters_and_grads):
        program = default_main_program()
        for p, g in parameters_and_grads:
            if g is None:
                continue
            with program._optimized_guard([p, g]):
                b1p = self._get_accumulator(self._beta1_pow_acc_str, p)
                block.append_op(
                    type="scale",
                    inputs={"X": [b1p.name]},
                    outputs={"Out": [b1p.name]},
                    attrs={"scale": self._beta1},
                )


class DecayedAdagradOptimizer(Optimizer):
    _moment_acc_str = "moment"

    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "decayed_adagrad"
        self._decay, self._epsilon = decay, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        moment = self._get_accumulator(self._moment_acc_str, p)
        return block.append_op(
            type="decayed_adagrad",
            inputs={
                "Param": [p.name],
                "Grad": [g.name],
                "Moment": [moment.name],
                "LearningRate": [self._create_param_lr(param_and_grad).name],
            },
            outputs={"ParamOut": [p.name], "MomentOut": [moment.name]},
            attrs={"decay": self._decay, "epsilon": self._epsilon},
        )


class AdadeltaOptimizer(Optimizer):
    _avg_squared_grad_acc_str = "_avg_squared_grad"
    _avg_squared_update_acc_str = "_avg_squared_update"

    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "adadelta"
        self._epsilon, self._rho = epsilon, rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._avg_squared_grad_acc_str, p)
            self._add_accumulator(self._avg_squared_update_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        asg = self._get_accumulator(self._avg_squared_grad_acc_str, p)
        asu = self._get_accumulator(self._avg_squared_update_acc_str, p)
        return block.append_op(
            type="adadelta",
            inputs={
                "Param": [p.name],
                "Grad": [g.name],
                "AvgSquaredGrad": [asg.name],
                "AvgSquaredUpdate": [asu.name],
            },
            outputs={
                "ParamOut": [p.name],
                "AvgSquaredGradOut": [asg.name],
                "AvgSquaredUpdateOut": [asu.name],
            },
            attrs={"epsilon": self._epsilon, "rho": self._rho},
        )


class RMSPropOptimizer(Optimizer):
    _momentum_acc_str = "momentum"
    _mean_square_acc_str = "mean_square"
    _mean_grad_acc_str = "mean_grad"

    def __init__(
        self,
        learning_rate,
        rho=0.95,
        epsilon=1e-6,
        momentum=0.0,
        centered=False,
        **kwargs,
    ):
        super().__init__(learning_rate, **kwargs)
        self.type = "rmsprop"
        self._rho, self._epsilon, self._momentum, self._centered = (
            rho,
            epsilon,
            momentum,
            centered,
        )

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._momentum_acc_str, p)
            self._add_accumulator(self._mean_square_acc_str, p)
            if self._centered:
                self._add_accumulator(self._mean_grad_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        momentum = self._get_accumulator(self._momentum_acc_str, p)
        mean_square = self._get_accumulator(self._mean_square_acc_str, p)
        inputs = {
            "Param": [p.name],
            "Grad": [g.name],
            "Moment": [momentum.name],
            "MeanSquare": [mean_square.name],
            "LearningRate": [self._create_param_lr(param_and_grad).name],
        }
        outputs = {
            "ParamOut": [p.name],
            "MomentOut": [momentum.name],
            "MeanSquareOut": [mean_square.name],
        }
        if self._centered:
            mg = self._get_accumulator(self._mean_grad_acc_str, p)
            inputs["MeanGrad"] = [mg.name]
            outputs["MeanGradOut"] = [mg.name]
        return block.append_op(
            type="rmsprop",
            inputs=inputs,
            outputs=outputs,
            attrs={
                "epsilon": self._epsilon,
                "decay": self._rho,
                "momentum": self._momentum,
                "centered": self._centered,
            },
        )


class FtrlOptimizer(Optimizer):
    _squared_acc_str = "squared"
    _linear_acc_str = "linear"

    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "ftrl"
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._squared_acc_str, p)
            self._add_accumulator(self._linear_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        sq = self._get_accumulator(self._squared_acc_str, p)
        lin = self._get_accumulator(self._linear_acc_str, p)
        return block.append_op(
            type="ftrl",
            inputs={
                "Param": [p.name],
                "Grad": [g.name],
                "SquaredAccumulator": [sq.name],
                "LinearAccumulator": [lin.name],
                "LearningRate": [self._create_param_lr(param_and_grad).name],
            },
            outputs={
                "ParamOut": [p.name],
                "SquaredAccumOut": [sq.name],
                "LinearAccumOut": [lin.name],
            },
            attrs={"l1": self._l1, "l2": self._l2, "lr_power": self._lr_power},
        )


class ProximalGDOptimizer(Optimizer):
    """reference optimizer.py ProximalGDOptimizer → optimizers/proximal_gd_op.cc"""

    def __init__(self, learning_rate, l1=0.0, l2=0.0, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "proximal_gd"
        self._l1, self._l2 = l1, l2

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            type="proximal_gd",
            inputs={
                "Param": [p.name],
                "Grad": [g.name],
                "LearningRate": [self._create_param_lr(param_and_grad).name],
            },
            outputs={"ParamOut": [p.name]},
            attrs={"l1": self._l1, "l2": self._l2},
        )


class ProximalAdagradOptimizer(Optimizer):
    """reference optimizer.py ProximalAdagradOptimizer →
    optimizers/proximal_adagrad_op.cc"""

    _moment_acc_str = "moment"

    def __init__(self, learning_rate, l1=0.0, l2=0.0, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "proximal_adagrad"
        self._l1, self._l2 = l1, l2

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        moment = self._get_accumulator(self._moment_acc_str, p)
        return block.append_op(
            type="proximal_adagrad",
            inputs={
                "Param": [p.name],
                "Grad": [g.name],
                "Moment": [moment.name],
                "LearningRate": [self._create_param_lr(param_and_grad).name],
            },
            outputs={"ParamOut": [p.name], "MomentOut": [moment.name]},
            attrs={"l1": self._l1, "l2": self._l2},
        )


class ModelAverage(Optimizer):
    """Sliding-window parameter averaging (reference optimizer.py ModelAverage
    → operators/average_accumulates_op.cc). Construct AFTER minimize();
    accumulation ops are appended to the main program for every parameter,
    and ``with model_average.apply(exe):`` swaps averaged weights in (restored
    on exit unless need_restore=False)."""

    def __init__(
        self,
        average_window_rate,
        min_average_window=10000,
        max_average_window=10000,
        **kwargs,
    ):
        super().__init__(0.0, **kwargs)
        self.type = "model_average"
        self.average_window = average_window_rate
        self.min_average_window = min_average_window
        self.max_average_window = max_average_window
        self.params_grads = [
            (p, None)
            for p in default_main_program().global_block().all_parameters()
        ]
        self.helper = LayerHelper(self.__class__.__name__)
        block = default_main_program().global_block()
        for p, _ in self.params_grads:
            self._append_average_accumulate_op(block, p)

    def _append_average_accumulate_op(self, block, param):
        sums = [
            self._add_accumulator("sum_%d" % i, param) for i in (1, 2, 3)
        ]
        counters = [
            self._add_accumulator(n, param, dtype="int64", shape=[1])
            for n in ("num_accumulates", "old_num_accumulates", "num_updates")
        ]
        names = [v.name for v in sums] + [v.name for v in counters]
        with default_main_program()._optimized_guard([param, None]):
            block.append_op(
                type="average_accumulates",
                inputs={
                    "Param": [param.name],
                    "Sums": names[:3],
                    "Counters": names[3:],
                },
                outputs={"SumsOut": names[:3], "CountersOut": names[3:]},
                attrs={
                    "average_window": self.average_window,
                    "min_average_window": self.min_average_window,
                    "max_average_window": self.max_average_window,
                },
            )

    def _build_swap_program(self, to_average):
        prog = framework.Program()
        with framework.program_guard(prog):
            block = prog.global_block()
            for p, _ in self.params_grads:
                # mirror vars by name so the shared scope resolves them
                for v in [p] + [
                    self._get_accumulator("sum_%d" % i, p) for i in (1, 2, 3)
                ] + [
                    self._get_accumulator(n, p)
                    for n in ("num_accumulates", "old_num_accumulates")
                ] + [self._backup_var(p)]:
                    if v.name not in block.vars:
                        block.create_var(
                            name=v.name,
                            shape=v.shape,
                            dtype=v.dtype,
                            persistable=True,
                        )
                if to_average:
                    block.append_op(
                        type="average_apply",
                        inputs={
                            "Param": [p.name],
                            "Sums": [
                                self._get_accumulator("sum_%d" % i, p).name
                                for i in (1, 2, 3)
                            ],
                            "Counters": [
                                self._get_accumulator(n, p).name
                                for n in ("num_accumulates", "old_num_accumulates")
                            ],
                        },
                        outputs={
                            "ParamOut": [p.name],
                            "Backup": [self._backup_var(p).name],
                        },
                    )
                else:
                    block.append_op(
                        type="assign",
                        inputs={"X": [self._backup_var(p).name]},
                        outputs={"Out": [p.name]},
                    )
        return prog

    def _backup_var(self, param):
        return self._add_accumulator("restore_backup", param)

    @contextlib.contextmanager
    def apply(self, executor, need_restore=True):
        executor.run(self._build_swap_program(to_average=True))
        try:
            yield
        finally:
            if need_restore:
                self.restore(executor)

    def restore(self, executor):
        executor.run(self._build_swap_program(to_average=False))


# short aliases matching fluid.optimizer public names
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
LarsMomentum = LarsMomentumOptimizer
ProximalGD = ProximalGDOptimizer
ProximalAdagrad = ProximalAdagradOptimizer
