"""Multiprocess decode workers for the native data runtime.

Reference analog: executor_thread_worker.cc — the AsyncExecutor's N parser
threads, each consuming a slice of the file list into the native blocking
queue. Python decode code (JPEG decode, augmentation, tokenization) cannot
scale across threads under the GIL, so the TPU-native analog uses
PROCESSES: each worker pulls shard ids from its assignment queue, runs the
user's ``decode_fn(shard_id)`` (an iterable of ``{name: ndarray}`` batches),
and writes every batch straight into a shared-memory ring slab (ring.py) —
the trainer process never unpickles an array payload.

SIGKILL-safe plumbing: every queue here has exactly ONE producer and ONE
consumer, and free-slot handoff uses no queue at all. A multiprocessing
queue shared by several workers is a kill hazard — its reader lock is held
for the whole duration of a blocking ``get`` and its pipe write lock for
each feeder flush, so killing the holder starves every surviving worker
forever. Instead each worker has its own assignment queue and its own
ready (descriptor) queue, both discarded and rebuilt on respawn, and ring
slots are statically partitioned per worker: worker ``w`` owns slots
``w, w+N, w+2N, ...`` and claims a free one with a plain aligned store in
the ring's shared control block (ring.try_claim), the consumer releasing
with the mirror store. No cross-process lock exists on the hot path.

Crash isolation: a worker is expendable. The parent (runtime.py) polls
liveness; when a worker dies it drains the stragglers from the dead ready
queue, reclaims the worker's ring slots, respawns the process under the
PR 1 resilience retry policy with fresh queues, and re-queues the in-flight
shards with ``skip`` = number of batches already received — decode is
required to be deterministic per shard, so the replay regenerates exactly
the batches that were lost, and the consumer's (shard, index) dedupe drops
any that survived in flight. Net effect: SIGKILL at any point loses zero
samples and duplicates none (tests/test_data_runtime.py).

fork vs spawn: both work (``FLAGS_data_start_method``). Workers never touch
jax — fork is safe and fast (no re-import); spawn additionally requires
``decode_fn`` to be picklable (a module-level callable), which is the shape
to use when the parent process already initialized a TPU backend.
"""

import queue as _queue
import time
import traceback

__all__ = ["WorkerPool", "home_slots", "shutdown_sentinel"]


def shutdown_sentinel():
    return None  # the assignment-queue item that tells a worker to exit


def home_slots(worker_id, num_workers, ring_slots):
    """The ring slots worker ``worker_id`` owns (static partition)."""
    return list(range(worker_id, ring_slots, num_workers))


class _Stop(Exception):
    pass


def _claim_slot(ring, slots, worker_id, stop_ev):
    """Spin over the worker's home slots until one is free. Lock-free: the
    consumer's release (owner := -1) is the only thing being waited on."""
    while True:
        for slot in slots:
            if ring.try_claim(slot, worker_id):
                return slot
        if stop_ev.is_set():
            raise _Stop()
        time.sleep(0.001)


def _worker_main(worker_id, num_workers, ring_name, decode_fn, shard_q,
                 ready_q, stop_ev, gen_cell):
    """Child-process entry point (module-level: picklable under spawn)."""
    from .ring import RingBuffer, SlabOverflowError

    ring = RingBuffer(0, 0, name=ring_name, create=False)
    slots = home_slots(worker_id, num_workers, ring.slots)
    try:
        while not stop_ev.is_set():
            try:
                item = shard_q.get(timeout=0.1)
            except _queue.Empty:
                continue
            if item is None:
                return
            shard_id, skip, gen = item
            ready_q.put(
                {"kind": "shard_start", "worker": worker_id, "shard": shard_id,
                 "gen": gen}
            )
            index = 0
            busy_ms = wait_ms = 0.0
            try:
                t0 = time.perf_counter()
                for batch in decode_fn(shard_id):
                    busy_ms += (time.perf_counter() - t0) * 1e3
                    if gen_cell.value != gen:
                        raise _Stop()  # epoch aborted: abandon the shard
                    if index >= skip:
                        tw = time.perf_counter()
                        slot = _claim_slot(ring, slots, worker_id, stop_ev)
                        wait_ms += (time.perf_counter() - tw) * 1e3
                        ring.begin_write(slot, worker_id)
                        try:
                            meta, nbytes = ring.pack(slot, batch)
                            seq = ring.commit(slot)
                        except BaseException:
                            # an aborted write may not leak the slot: make
                            # the seq even again and hand the slot back
                            ring.commit(slot)
                            ring.release(slot)
                            raise
                        ready_q.put(
                            {"kind": "batch", "worker": worker_id,
                             "shard": shard_id, "index": index, "slot": slot,
                             "seq": seq, "meta": meta, "bytes": nbytes,
                             "gen": gen, "busy_ms": busy_ms, "wait_ms": wait_ms}
                        )
                        busy_ms = wait_ms = 0.0
                    index += 1
                    t0 = time.perf_counter()
                ready_q.put(
                    {"kind": "shard_done", "worker": worker_id,
                     "shard": shard_id, "batches": index, "gen": gen}
                )
            except _Stop:
                continue
            except SlabOverflowError as e:
                ready_q.put(
                    {"kind": "error", "worker": worker_id, "shard": shard_id,
                     "gen": gen, "error": repr(e), "fatal": True,
                     "trace": traceback.format_exc()}
                )
            except BaseException as e:  # noqa: B036 — carried to the trainer
                ready_q.put(
                    {"kind": "error", "worker": worker_id, "shard": shard_id,
                     "gen": gen, "error": repr(e), "fatal": False,
                     "trace": traceback.format_exc()}
                )
    finally:
        ring.close()


class WorkerPool:
    """Owns the worker processes and their per-worker queues; the runtime
    owns all bookkeeping (shard accounting lives where the ready queues are
    drained). ``queue(w)`` / ``ready_queue(w)`` return the CURRENT queues —
    a respawn replaces both (the dead worker's queues may hold poisoned
    locks or truncated pickles, and anything still inside them was already
    re-queued or superseded by the parent's authoritative records)."""

    def __init__(self, ctx, num_workers, ring_name, decode_fn,
                 max_restarts=4):
        from ..resilience.retry import RetryPolicy

        self.ctx = ctx
        self.num_workers = int(num_workers)
        self.ring_name = ring_name
        self.decode_fn = decode_fn
        self.stop_ev = ctx.Event()
        self.gen_cell = ctx.Value("l", 0, lock=False)
        # respawn cadence rides the unified resilience policy: bounded
        # attempts with jittered exponential backoff per worker slot
        self.restart_policy = RetryPolicy(
            max_attempts=max(1, int(max_restarts)), base_delay=0.05,
            max_delay=2.0, deadline=None,
        )
        self.restarts = [0] * self.num_workers
        self.procs = [None] * self.num_workers
        self._shard_qs = [ctx.Queue() for _ in range(self.num_workers)]
        self._ready_qs = [ctx.Queue() for _ in range(self.num_workers)]

    def queue(self, worker_id):
        return self._shard_qs[worker_id]

    def ready_queue(self, worker_id):
        return self._ready_qs[worker_id]

    def _spawn(self, worker_id):
        p = self.ctx.Process(
            target=_worker_main,
            args=(worker_id, self.num_workers, self.ring_name, self.decode_fn,
                  self._shard_qs[worker_id], self._ready_qs[worker_id],
                  self.stop_ev, self.gen_cell),
            daemon=True,
            name="ptdata-worker-%d" % worker_id,
        )
        p.start()
        self.procs[worker_id] = p
        return p

    def start(self):
        for w in range(self.num_workers):
            self._spawn(w)

    def dead_workers(self):
        return [
            w for w, p in enumerate(self.procs)
            if p is not None and not p.is_alive()
        ]

    def respawn(self, worker_id):
        """Respawn a dead worker with FRESH queues, under the retry policy.
        Returns False when the slot has exhausted its restart budget (the
        runtime then surfaces a fatal error instead of spinning on a crash
        loop)."""
        self.restarts[worker_id] += 1
        attempt = self.restarts[worker_id]
        if attempt > self.restart_policy.max_attempts:
            return False
        old = self.procs[worker_id]
        if old is not None:
            old.join(timeout=1.0)
        self._shard_qs[worker_id] = self.ctx.Queue()
        self._ready_qs[worker_id] = self.ctx.Queue()
        time.sleep(self.restart_policy.backoff(attempt - 1))
        self._spawn(worker_id)
        return True

    def set_generation(self, gen):
        self.gen_cell.value = int(gen)

    def stop(self, join_timeout=5.0):
        self.stop_ev.set()
        for q in self._shard_qs:
            try:
                q.put_nowait(shutdown_sentinel())
            except Exception:  # noqa: BLE001 — queue may be full/closed
                pass
        deadline = time.time() + join_timeout
        for p in self.procs:
            if p is None:
                continue
            p.join(timeout=max(0.1, deadline - time.time()))
            if p.is_alive():
                p.terminate()
                p.join(timeout=1.0)
            if p.is_alive() and hasattr(p, "kill"):
                p.kill()
                p.join(timeout=1.0)
        self.procs = [None] * self.num_workers

    def pids(self):
        return [p.pid if p is not None else None for p in self.procs]
