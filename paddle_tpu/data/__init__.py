"""Native data runtime: multiprocess decode workers + shared-memory ring
buffer + async double-buffered device feed (docs/data.md).

The paper's L2 AsyncExecutor/DataFeed layer rebuilt TPU-first: decode
parallelism moves to processes (the GIL owns threads), the hand-off is a
shared-memory ring of batch slabs (zero pickling of payloads), datasets
shard per host and per worker deterministically, and batch k+1 is
device_put while step k runs. ``PyReader.decorate_paddle_reader(...,
num_workers=N)`` is the drop-in front end; ``DataRuntime`` is the native
shard-based API; ``AsyncExecutor.run`` rides the same pool for its
filelist. ``cache_epoch`` (PR 3) remains the opt-in fast path for datasets
that fit in HBM — this runtime is for the ones that don't.
"""

from .ring import RingBuffer, SlabOverflowError, TornSlotError
from .runtime import DataRuntime
from .sharding import epoch_shard_order, host_shards, worker_shards

__all__ = [
    "DataRuntime",
    "RingBuffer",
    "SlabOverflowError",
    "TornSlotError",
    "epoch_shard_order",
    "host_shards",
    "worker_shards",
]
