"""Shared-memory ring buffer of fixed-size batch slabs.

Reference analog: operators/reader/lod_tensor_blocking_queue.h — the native
bounded queue decode threads filled while the trainer popped. That queue
lived in one C++ process; here the decode workers are PROCESSES (Python
parse/augment code does not scale across threads under the GIL), so the
hand-off memory is ``multiprocessing.shared_memory``: one segment per ring
slot, sized for one packed batch. Workers write decoded arrays directly
into a slab the trainer process has mapped — the array payload crosses the
process boundary with zero pickling and zero extra copies; only a tiny
descriptor (slot index, field shapes/dtypes/offsets, seq) travels over a
queue.

Slot life cycle (single-writer-per-slot discipline):

    free -> claimed by one worker -> begin_write (seq EVEN->ODD, owner=wid)
         -> payload memcpy into slab -> commit (seq ODD->EVEN)
         -> descriptor to the trainer -> trainer copies out -> release(free)

The per-slot uint64 ``seq`` is a seqlock-style ready flag: ODD means a
write is in flight, EVEN means stable, and the committed value rides the
descriptor so the consumer can verify the slab is exactly the write the
descriptor announced (before AND after its copy-out). A worker that dies
mid-write leaves its slot ODD with its owner id in the control block;
``reclaim_dead`` bumps such slots back to EVEN so the supervisor can return
them to the free pool — the half-written payload can never be served
because no descriptor carries the new seq.

Aligned 8-byte loads/stores are atomic on every platform jax runs on, and
each seq cell has exactly one writer at a time, so no cross-process lock is
needed on the hot path.
"""

import os
import struct

import numpy as np

__all__ = ["RingBuffer", "TornSlotError", "SlabOverflowError"]

_MAGIC = 0x70746472  # 'ptdr'


class TornSlotError(RuntimeError):
    """Slab content no longer matches the descriptor's committed seq —
    the protocol was violated (or a reclaimed slot raced); the batch must
    be dropped, never served."""


class SlabOverflowError(ValueError):
    """A packed batch exceeds slot_bytes; raise with the size needed so
    the caller can re-create the ring with bigger slabs."""


def _attach(name):
    """Attach an existing segment WITHOUT resource-tracker registration:
    the creating process owns unlink (bpo-38119). All processes of one
    family share ONE tracker, so an attach-side register/unregister pair
    would strip the creator's registration and spam KeyError tracebacks;
    instead the register call is suppressed for the attach itself."""
    from multiprocessing import resource_tracker, shared_memory

    orig = resource_tracker.register
    resource_tracker.register = lambda *a, **kw: None
    try:
        return shared_memory.SharedMemory(name=name)
    finally:
        resource_tracker.register = orig


class RingBuffer:
    """``create=True`` builds the segments (trainer side, owns unlink);
    ``create=False`` attaches by name (worker side)."""

    def __init__(self, slots, slot_bytes, name=None, create=True):
        from multiprocessing import shared_memory

        if create:
            if slots < 1:
                raise ValueError("need at least 1 slot, got %r" % (slots,))
            if slot_bytes < 64:
                raise ValueError("slot_bytes too small: %r" % (slot_bytes,))
            if name is None:
                name = "ptd%x-%s" % (os.getpid() & 0xFFFFFF, os.urandom(3).hex())
        self.name = name
        self.owns = bool(create)
        ctl_name = name + "-ctl"
        # control block: [magic u32, slots u32, slot_bytes u64] header, then
        # per-slot seq (u64) and owner (i32, -1 = unowned)
        hdr = struct.calcsize("<IIQ")
        if create:
            ctl_bytes = hdr + slots * (8 + 4)
            self._ctl = shared_memory.SharedMemory(
                name=ctl_name, create=True, size=ctl_bytes
            )
            struct.pack_into("<IIQ", self._ctl.buf, 0, _MAGIC, slots, slot_bytes)
        else:
            self._ctl = _attach(ctl_name)
            magic, slots, slot_bytes = struct.unpack_from("<IIQ", self._ctl.buf, 0)
            if magic != _MAGIC:
                raise RuntimeError("bad ring control block %r" % (ctl_name,))
        self.slots = int(slots)
        self.slot_bytes = int(slot_bytes)
        self._seq = np.frombuffer(
            self._ctl.buf, dtype=np.uint64, count=self.slots, offset=hdr
        )
        self._owner = np.frombuffer(
            self._ctl.buf,
            dtype=np.int32,
            count=self.slots,
            offset=hdr + self.slots * 8,
        )
        if create:
            self._owner[:] = -1
        self._slabs = []
        for i in range(self.slots):
            seg_name = "%s-s%d" % (name, i)
            if create:
                seg = shared_memory.SharedMemory(
                    name=seg_name, create=True, size=self.slot_bytes
                )
            else:
                seg = _attach(seg_name)
            self._slabs.append(seg)

    # --- writer side (one claiming worker per slot) ---
    def try_claim(self, slot, owner):
        """Lock-free slot claim. Slots are statically partitioned per
        worker (slot s belongs to worker s % num_workers), so for any slot
        there is exactly ONE claimer — the handoff is a plain aligned store:
        the consumer releases by writing owner=-1, the home worker claims by
        writing its id back. No cross-process lock exists to be poisoned by
        a SIGKILL (a mp.Queue of free slots would hold its reader lock for
        the whole get() poll — killing the holder starves every worker)."""
        if int(self._owner[slot]) != -1:
            return False
        self._owner[slot] = np.int32(owner)
        return True

    def begin_write(self, slot, owner):
        self._owner[slot] = np.int32(owner)
        self._seq[slot] += np.uint64(1)  # EVEN -> ODD: write in flight

    def pack(self, slot, feed):
        """memcpy each array of ``feed`` (dict name -> ndarray) into the
        slab, back to back. Returns (meta, nbytes): meta is the descriptor
        payload [(name, shape, dtype_str, offset)] the consumer needs to
        rebuild views — small, picklable, and the ONLY thing that leaves
        this process through a queue."""
        meta = []
        off = 0
        buf = self._slabs[slot].buf
        for name in sorted(feed):
            arr = np.ascontiguousarray(feed[name])
            nb = arr.nbytes
            if off + nb > self.slot_bytes:
                raise SlabOverflowError(
                    "batch needs %d bytes but ring slots hold %d — pass a "
                    "bigger slot_bytes / batch_spec to the runtime"
                    % (off + nb, self.slot_bytes)
                )
            if nb:
                buf[off : off + nb] = arr.reshape(-1).view(np.uint8).data
            # extension dtypes (ml_dtypes bfloat16 etc.) stringify to a raw
            # void via .str; their registered .name round-trips instead
            dt = arr.dtype
            dt_s = dt.name if dt.kind == "V" else dt.str
            meta.append((name, tuple(arr.shape), dt_s, off))
            off += nb
        return meta, off

    def commit(self, slot):
        self._seq[slot] += np.uint64(1)  # ODD -> EVEN: stable
        return int(self._seq[slot])

    # --- consumer side (trainer process) ---
    def seq(self, slot):
        return int(self._seq[slot])

    def read(self, slot, meta, expect_seq):
        """Copy the packed fields back out as owned ndarrays, verifying the
        seqlock before and after the copy. The copy is deliberate: the
        returned arrays must survive slot reuse, and jax.device_put on the
        CPU backend may alias a host buffer instead of copying it."""
        s0 = int(self._seq[slot])
        if s0 != expect_seq or s0 % 2 == 1:
            raise TornSlotError(
                "slot %d seq %d != descriptor seq %d" % (slot, s0, expect_seq)
            )
        out = {}
        buf = self._slabs[slot].buf
        for name, shape, dtype_str, off in meta:
            try:
                dt = np.dtype(dtype_str)
            except TypeError:
                import ml_dtypes  # noqa: F401 — registers bfloat16 et al.

                dt = np.dtype(dtype_str)
            n = int(np.prod(shape, dtype=np.int64)) if shape else 1
            view = np.frombuffer(buf, dtype=dt, count=n, offset=off)
            out[name] = view.reshape(shape).copy()
        s1 = int(self._seq[slot])
        if s1 != s0:
            raise TornSlotError(
                "slot %d overwritten during read (seq %d -> %d)" % (slot, s0, s1)
            )
        return out

    def release(self, slot):
        self._owner[slot] = -1

    def owned_slots(self):
        """Slot indices currently claimed by any worker (mid-write or
        committed-but-undelivered) — the ring-occupancy gauge."""
        return [s for s in range(self.slots) if int(self._owner[s]) != -1]

    # --- supervisor side ---
    def reclaim_dead(self, owner_ids):
        """Slots a dead worker left claimed: ODD seq (mid-write) is bumped
        to the next EVEN value — no descriptor references it, so the torn
        payload is unreachable — and the slot is released so the respawned
        home worker can claim it again. Committed slots whose descriptor
        died with the worker's queue are released the same way (the queue
        is discarded on respawn, so no straggler descriptor can resurface).
        Returns the reclaimed slot indices."""
        owner_ids = set(int(w) for w in owner_ids)
        out = []
        for slot in range(self.slots):
            if int(self._owner[slot]) in owner_ids:
                if int(self._seq[slot]) % 2:
                    self._seq[slot] += np.uint64(1)
                self._owner[slot] = -1
                out.append(slot)
        return out

    def close(self):
        # release numpy views of the mapped buffers before closing the maps
        self._seq = self._owner = None
        for seg in [self._ctl] + self._slabs:
            try:
                seg.close()
            except Exception:  # noqa: BLE001
                pass
        if self.owns:
            for seg in [self._ctl] + self._slabs:
                try:
                    seg.unlink()
                except Exception:  # noqa: BLE001
                    pass
        self._slabs = []
