"""Deterministic dataset sharding for the native data runtime.

Reference analog: the AsyncExecutor sharded its filelist across parser
threads (async_executor.cc hands each executor_thread_worker a slice of the
file list), and the distributed fluid reader idiom was
``reader = shard(reader, trainer_num, trainer_id)``. Here the same two
levels exist as pure, testable functions:

- HOST level: ``host_shards(order, num_hosts, host_id)`` — every host of a
  multihost run owns a disjoint strided slice of the epoch's shard order,
  so input work is never duplicated across hosts (each sample is decoded by
  exactly one host per epoch).
- WORKER level: within a host, workers pull shards dynamically from a
  shared queue whose order IS the host slice (load balancing without
  losing determinism of the set); ``worker_shards`` gives the static
  sub-assignment used when a fixed mapping is required (tests, skip-replay
  accounting).

The epoch order itself is a seeded permutation: same (seed, epoch) -> same
order on every host, different epochs -> different order. All functions are
pure so the (num_hosts, num_workers) grid properties — disjointness, full
coverage, determinism — are directly unit-testable.
"""

import numpy as np

__all__ = ["epoch_shard_order", "host_shards", "worker_shards"]


def epoch_shard_order(num_shards, seed=0, epoch=0, shuffle=True):
    """Deterministic shard visit order for one epoch: a permutation of
    range(num_shards) seeded by (seed, epoch). Identical on every host —
    the per-host slice is taken AFTER the shuffle, so reshuffling between
    epochs never breaks host disjointness."""
    if num_shards < 0:
        raise ValueError("num_shards must be >= 0, got %r" % (num_shards,))
    ids = np.arange(num_shards, dtype=np.int64)
    if shuffle and num_shards > 1:
        # mix epoch into the seed with a large odd multiplier so (seed=1,
        # epoch=0) and (seed=0, epoch=1) don't collide
        rng = np.random.RandomState((int(seed) * 1000003 + int(epoch)) % (2**32))
        ids = rng.permutation(ids)
    return [int(i) for i in ids]


def _check_part(num, idx, what):
    if num < 1:
        raise ValueError("num_%ss must be >= 1, got %r" % (what, num))
    if not (0 <= idx < num):
        raise ValueError(
            "%s_id %r out of range for num_%ss=%r" % (what, idx, what, num)
        )


def host_shards(order, num_hosts, host_id):
    """This host's strided slice of the epoch order. Disjoint and covering
    across host_id in range(num_hosts); |slice| differs by at most 1."""
    _check_part(num_hosts, host_id, "host")
    return list(order[host_id::num_hosts])


def worker_shards(order, num_workers, worker_id):
    """Static per-worker sub-shard of a host's shard list (strided). The
    runtime's pool assigns shards dynamically from a queue in this same
    list order; this function is the static equivalent for deterministic
    replay and for the grid tests."""
    _check_part(num_workers, worker_id, "worker")
    return list(order[worker_id::num_workers])
