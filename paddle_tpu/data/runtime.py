"""DataRuntime: the trainer-side orchestrator of the native data runtime.

Paper/reference analog: the L2 AsyncExecutor/DataFeed layer — N parser
threads filling a native blocking queue the trainer pops. The TPU-native
composition here (docs/data.md):

    decode workers (processes, workers.py)
        -> shared-memory ring slabs (ring.py; payload never pickled)
        -> drain thread: seqlock-validate, copy out, dedupe, release slot,
           async jax.device_put (batch k+1 transfers while step k runs)
        -> bounded staged queue of device-resident batches
        -> next_batch() (Executor / ParallelExecutor / PyReader pull here)

Exactly-once contract: every (shard, batch index) is delivered at most once
(consumer-side dedupe) and at least once (authoritative parent-side shard
assignment: a dead worker's outstanding shards are re-queued with
``skip`` = batches already received, and decode is deterministic per
shard). SIGKILLing a worker mid-epoch therefore loses nothing and
duplicates nothing — tests/test_data_runtime.py proves this with a real
kill, in the style of tests/test_resilience.py.

Observability (docs/observability.md): the runtime feeds the PR 4 metric
registry — data/ring_occupancy, data/bytes_per_sec, per-worker
data/worker_busy_frac and data/batches_total, data/worker_restarts — and
``next_batch`` records time blocked on the staged queue as feed-stall in
StepStats, so `pyreader_frac` measures TRUE overlap end to end.
"""

import collections
import queue as _queue
import threading
import time

import numpy as np

from .ring import RingBuffer, TornSlotError
from .sharding import epoch_shard_order, host_shards
from .workers import WorkerPool

__all__ = ["DataRuntime"]

_OUTSTANDING_PER_WORKER = 2  # active shard + one prefetched assignment


def _flags():
    from ..flags import get_flags

    return get_flags()


def _registry():
    from ..observability.registry import default_registry

    return default_registry()


class _Eof:
    def __init__(self, gen):
        self.gen = gen


class _Error:
    def __init__(self, gen, exc):
        self.gen = gen
        self.exc = exc


def spec_bytes(batch_spec):
    """Packed slab bytes for a {name: (shape, dtype)} batch spec."""
    total = 0
    for shape, dtype in batch_spec.values():
        total += int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
    return total


class DataRuntime:
    def __init__(self, decode_fn, num_shards, num_workers=None,
                 ring_slots=None, slot_bytes=None, batch_spec=None,
                 num_hosts=1, host_id=0, seed=0, shuffle=True,
                 start_method=None, device_prefetch=None, stage_device=True,
                 device_sharding=None, max_worker_restarts=None, name="data"):
        """decode_fn(shard_id) -> iterable of {name: ndarray} batches; MUST
        be deterministic per shard_id (the crash-replay contract) and must
        not touch jax (it runs in worker processes). Under
        FLAGS_data_start_method=spawn it must also be picklable."""
        flags = _flags()
        self.decode_fn = decode_fn
        self.num_shards = int(num_shards)
        self.num_workers = int(num_workers or flags["data_num_workers"] or 2)
        self.ring_slots = int(
            ring_slots or flags["data_ring_slots"]
            or max(4, 2 * self.num_workers)
        )
        self.ring_slots = max(self.ring_slots, self.num_workers + 1)
        self._slot_bytes = slot_bytes
        self._batch_spec = batch_spec
        self.num_hosts = int(num_hosts)
        self.host_id = int(host_id)
        self.seed = int(seed)
        self.shuffle = bool(shuffle)
        self.prefetch = int(device_prefetch or flags["data_prefetch"] or 2)
        self.stage_device = bool(stage_device)
        self.device_sharding = device_sharding
        self._start_method = start_method or flags["data_start_method"]
        self._max_restarts = (
            max_worker_restarts
            if max_worker_restarts is not None
            else flags["data_max_worker_restarts"]
        )
        self.name = name

        self._ctx = None
        self._ring = None
        self._pool = None
        self._drain = None
        self._lock = threading.RLock()
        self._gen = 0
        self._epoch = -1
        self._started = False
        self._closed = False
        self._staged = _queue.Queue(maxsize=max(1, self.prefetch))
        self._stats_t0 = time.perf_counter()
        self._stats_bytes = 0
        # per-epoch accounting (under _lock)
        self._pending = collections.deque()
        self._assigned = {}  # worker -> [shard ids outstanding, in order]
        self._received = {}  # shard -> contiguous received count
        self._remaining = set()

    # ------------------------------------------------------------------ setup
    def _ensure_pool(self):
        if self._pool is not None:
            return
        import multiprocessing as mp

        if self._slot_bytes is None:
            if self._batch_spec is not None:
                self._slot_bytes = spec_bytes(self._batch_spec)
            else:
                self._slot_bytes = self._probe_slot_bytes()
        # headroom: decode may bucket widths per batch; 25% + a page
        self._slot_bytes = int(self._slot_bytes * 1.25) + 4096
        self._ctx = mp.get_context(self._start_method)
        self._ring = RingBuffer(self.ring_slots, self._slot_bytes, create=True)
        self._pool = WorkerPool(
            self._ctx, self.num_workers, self._ring.name, self.decode_fn,
            max_restarts=self._max_restarts,
        )
        self._pool.start()
        self._drain = threading.Thread(
            target=self._drain_loop, daemon=True,
            name="ptdata-drain-%s" % self.name,
        )
        self._drain.start()

    def _probe_slot_bytes(self):
        """Decode ONE batch of the first shard in the parent to size the
        slabs. Costs one batch of decode; pass slot_bytes/batch_spec to
        skip (mandatory when batch sizes vary upward after the first)."""
        order = epoch_shard_order(self.num_shards, self.seed, 0, self.shuffle)
        mine = host_shards(order, self.num_hosts, self.host_id)
        if not mine:
            return 1 << 16
        for batch in self.decode_fn(mine[0]):
            total = sum(
                np.ascontiguousarray(v).nbytes for v in batch.values()
            )
            return max(total, 1 << 12)
        return 1 << 16

    # ------------------------------------------------------------- lifecycle
    @property
    def started(self):
        return self._started

    def start(self, epoch=None):
        """Begin an epoch: shuffle -> host shard -> assign to workers."""
        if self._closed:
            raise RuntimeError("DataRuntime is closed")
        if self._started:
            raise RuntimeError("epoch already running; call reset() first")
        self._ensure_pool()
        with self._lock:
            self._epoch = self._epoch + 1 if epoch is None else int(epoch)
            self._gen += 1
            self._pool.set_generation(self._gen)
            order = epoch_shard_order(
                self.num_shards, self.seed, self._epoch, self.shuffle
            )
            mine = host_shards(order, self.num_hosts, self.host_id)
            self._pending = collections.deque(mine)
            self._remaining = set(mine)
            self._received = {s: 0 for s in mine}
            self._assigned = {w: [] for w in range(self.num_workers)}
            self._started = True
            if not mine:
                self._staged.put(_Eof(self._gen))
            else:
                for w in range(self.num_workers):
                    self._top_up(w)
        try:
            _registry().counter(
                "data/epochs", "epochs started by the data runtime"
            ).inc()
        except Exception:  # noqa: BLE001 — telemetry must never break input
            pass

    def _top_up(self, worker):
        """Assign pending shards to ``worker`` until it has its outstanding
        quota. Caller holds _lock. Parent-side ``_assigned`` is the
        authoritative record — a dead worker's outstanding shards are
        recovered from here, never from worker acks."""
        q = self._pool.queue(worker)
        while self._pending and len(self._assigned[worker]) < _OUTSTANDING_PER_WORKER:
            shard = self._pending.popleft()
            self._assigned[worker].append(shard)
            q.put((shard, self._received.get(shard, 0), self._gen))

    def reset(self):
        """Abort the running epoch (PyReader.reset contract): stale
        generations drain harmlessly — workers abandon stale shards at the
        next batch, and the drain thread releases stale slots on sight."""
        with self._lock:
            self._gen += 1
            if self._pool is not None:
                self._pool.set_generation(self._gen)
            self._started = False
            self._pending.clear()
            self._remaining = set()
            self._assigned = {w: [] for w in range(self.num_workers)}
        while True:  # drop already-staged batches of the dead generation
            try:
                self._staged.get_nowait()
            except _queue.Empty:
                break

    def drain(self):
        """Preemption half-close (resilience/elastic.py Supervisor): abort
        the epoch, drop staged batches, and count the drop — then the
        caller close()s. Exactly-once across the preemption is carried by
        the checkpoint manifest's data cursor, not by preserving in-flight
        batches (a preempted host's ring is gone anyway)."""
        dropped = self._staged.qsize()
        self.reset()
        if dropped:
            from ..resilience import health as _health

            _health.incr("drain_batches_dropped", dropped)
        return dropped

    def close(self):
        if self._closed:
            return
        self.reset()
        self._closed = True
        if self._pool is not None:
            self._pool.stop()
        if self._drain is not None:
            self._drain.join(timeout=5)
        if self._ring is not None:
            self._ring.close()

    def __del__(self):  # best-effort: unlink shm segments
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass

    # -------------------------------------------------------------- consumer
    def next_batch(self):
        """Next device-staged batch; raises EOFException at epoch end.
        Blocking time here IS the input pipeline failing to keep up — it is
        recorded as feed-stall (stepstats), the overlap ground truth."""
        from ..py_reader import EOFException
        from ..observability import stepstats as _ss

        if not self._started:
            raise RuntimeError("DataRuntime epoch not started")
        t0 = time.perf_counter() if _ss.active() else None
        while True:
            try:
                item = self._staged.get(timeout=5.0)
            except _queue.Empty:
                if self._drain is not None and not self._drain.is_alive():
                    raise RuntimeError("data runtime drain thread died")
                continue
            if isinstance(item, (_Eof, _Error)) and item.gen != self._gen:
                continue  # stale epoch leftovers
            if isinstance(item, tuple) and item[0] != self._gen:
                continue
            break
        if t0 is not None:
            _ss.collector().add_feed_stall((time.perf_counter() - t0) * 1e3)
        if isinstance(item, _Eof):
            self._started = False
            raise EOFException("data runtime epoch exhausted")
        if isinstance(item, _Error):
            self._started = False
            raise item.exc
        return item[1]

    def __call__(self):
        from ..py_reader import EOFException

        try:
            while True:
                yield self.next_batch()
        except EOFException:
            return

    # ----------------------------------------------------------- drain loop
    def _put_control(self, item):
        """Deliver an _Eof/_Error to the staged queue without deadlocking
        against a full queue: give up as soon as its generation is stale
        (next_batch drops stale control items anyway)."""
        while True:
            with self._lock:
                if item.gen != self._gen:
                    return
            try:
                self._staged.put(item, timeout=0.1)
                return
            except _queue.Full:
                continue

    def _stage(self, gen, feed):
        """Optionally device_put (async — the transfer overlaps the running
        step) and hand to the bounded staged queue, staying responsive to
        generation bumps so an abort can't deadlock a full queue."""
        if self.stage_device:
            import jax

            sharding = self.device_sharding
            staged = {}
            for k, v in feed.items():
                sh = None
                if sharding is not None:
                    sh = sharding(v) if callable(sharding) else sharding
                staged[k] = (
                    jax.device_put(v, sh) if sh is not None else jax.device_put(v)
                )
            feed = staged
        while True:
            with self._lock:
                if gen != self._gen:
                    return
            try:
                self._staged.put((gen, feed), timeout=0.1)
                return
            except _queue.Full:
                continue

    def _drain_loop(self):
        """Round-robin over the per-worker ready queues. Each queue has one
        producer (its worker) and one consumer (this thread), so per-shard
        batch indices arrive in order by construction — and a message is
        always handled the moment it is fetched, BEFORE any supervisor
        work, so a recovery grace-drain can never leapfrog a held batch
        (the dedupe would drop it as a replay duplicate)."""
        last_liveness = 0.0
        while not self._closed:
            did_work = False
            for w in range(self.num_workers):
                try:
                    msg = self._pool.ready_queue(w).get_nowait()
                except _queue.Empty:
                    continue
                except Exception:  # noqa: BLE001 — poisoned/dead queue:
                    continue  # recovery will replace it
                did_work = True
                try:
                    self._handle(msg)
                except Exception as e:  # noqa: BLE001 — surface to trainer
                    with self._lock:
                        gen = self._gen
                    self._staged.put(_Error(gen, e))
            now = time.perf_counter()
            if now - last_liveness > 0.25:
                last_liveness = now
                try:
                    self._check_workers()
                    self._update_gauges()
                except Exception:  # noqa: BLE001 — supervisor must survive
                    pass
            if not did_work:
                time.sleep(0.005)

    def _handle(self, msg):
        kind = msg.get("kind")
        if kind == "batch":
            self._handle_batch(msg)
        elif kind == "shard_done":
            eof_gen = None
            with self._lock:
                if msg["gen"] != self._gen:
                    return
                shard, worker = msg["shard"], msg["worker"]
                if shard in self._remaining:
                    self._remaining.discard(shard)
                if shard in self._assigned.get(worker, []):
                    self._assigned[worker].remove(shard)
                self._top_up(worker)
                if self._started and not self._remaining and not self._pending:
                    eof_gen = self._gen
            if eof_gen is not None:
                self._put_control(_Eof(eof_gen))
        elif kind == "error":
            exc = RuntimeError(
                "data worker %s failed decoding shard %s: %s\n%s"
                % (msg["worker"], msg["shard"], msg["error"],
                   msg.get("trace", ""))
            )
            with self._lock:
                gen = self._gen
            if msg["gen"] == gen:
                self._put_control(_Error(gen, exc))
        # shard_start is informational (workers ack assignments); the
        # authoritative assignment record is parent-side _assigned

    def _handle_batch(self, msg):
        slot, seq = msg["slot"], msg["seq"]
        with self._lock:
            current = msg["gen"] == self._gen
            # per-shard indices arrive in order from a single live worker;
            # a crash-replay re-emits a contiguous prefix
            dup = current and msg["index"] < self._received.get(msg["shard"], 0)
        if not current or dup:
            self._ring.release(slot)
            if dup:
                try:
                    _registry().counter(
                        "data/batches_dropped_dup",
                        "crash-replay duplicates dropped by dedupe",
                    ).inc()
                except Exception:  # noqa: BLE001
                    pass
            return
        try:
            feed = self._ring.read(slot, msg["meta"], seq)
        except TornSlotError:
            # protocol kept us honest: never serve a torn slab. Do NOT
            # release — a torn seq means the slot was already reclaimed
            # and some writer may hold it now.
            return
        self._ring.release(slot)
        # count the batch as received only once it is safely copied out —
        # a torn read above must leave it claimable by the crash-replay
        with self._lock:
            if msg["gen"] != self._gen:
                return
            got = self._received.get(msg["shard"], 0)
            self._received[msg["shard"]] = max(got, msg["index"] + 1)
        self._account(msg)
        self._stage(msg["gen"], feed)

    def _account(self, msg):
        try:
            reg = _registry()
            w = str(msg["worker"])
            reg.counter(
                "data/batches_total", "batches delivered by decode workers"
            ).inc(1, worker=w)
            reg.counter(
                "data/bytes_total", "payload bytes through the shm ring"
            ).inc(msg["bytes"])
            busy, wait = msg.get("busy_ms", 0.0), msg.get("wait_ms", 0.0)
            if busy + wait > 0:
                reg.gauge(
                    "data/worker_busy_frac",
                    "decode time / (decode + ring-wait) per worker",
                ).set(busy / (busy + wait), worker=w)
            self._stats_bytes += msg["bytes"]
        except Exception:  # noqa: BLE001
            pass

    def _update_gauges(self):
        reg = _registry()
        reg.gauge(
            "data/ring_occupancy",
            "fraction of ring slots claimed (mid-write or undelivered)",
        ).set(len(self._ring.owned_slots()) / float(self.ring_slots))
        dt = time.perf_counter() - self._stats_t0
        if dt >= 1.0:
            reg.gauge(
                "data/bytes_per_sec", "shm ring payload throughput"
            ).set(self._stats_bytes / dt)
            self._stats_t0 = time.perf_counter()
            self._stats_bytes = 0

    def _check_workers(self):
        if self._pool is None:
            return
        for w in self._pool.dead_workers():
            self._recover_worker(w)

    def _recover_worker(self, w):
        """A worker died. Recover in this order: (1) drain its straggler
        messages, (2) re-queue its outstanding shards with skip=received,
        (3) reclaim/scavenge its ring slots, (4) respawn under the retry
        policy. docs/data.md#crash-isolation walks through why this is
        exactly-once."""
        try:
            from ..resilience import health

            health.incr("data_worker_death")
        except Exception:  # noqa: BLE001
            pass
        try:
            _registry().counter(
                "data/worker_restarts", "decode worker respawns"
            ).inc()
        except Exception:  # noqa: BLE001
            pass
        # (1) grace-drain: messages the dead worker flushed before dying.
        # Only ITS ready queue is read (per-worker queues), so the live
        # workers' streams cannot be reordered by this drain; the queue is
        # discarded on respawn, so nothing can straggle in later.
        rq = self._pool.ready_queue(w)
        while True:
            try:
                msg = rq.get(timeout=0.1)
            except _queue.Empty:
                break
            except Exception:  # noqa: BLE001 — truncated pickle etc.
                break
            try:
                self._handle(msg)
            except Exception:  # noqa: BLE001
                break
        with self._lock:
            # (2) outstanding shards back to pending, front of the line
            for shard in reversed(self._assigned.get(w, [])):
                if shard in self._remaining:
                    self._pending.appendleft(shard)
            self._assigned[w] = []
            # (3) the dead worker's ring slots — mid-write (seq forced
            # even; no descriptor carries the new seq) and committed-but-
            # undelivered alike — go back to claimable
            self._ring.reclaim_dead([w])
            # (4) respawn with fresh queues (the old ones may hold a
            # poisoned lock or a half-written pickle)
            ok = self._pool.respawn(w)
            if ok:
                for ww in range(self.num_workers):
                    self._top_up(ww)
            exhausted = not ok and self._started
            gen = self._gen
        if exhausted:
            self._put_control(_Error(gen, RuntimeError(
                "data worker %d exceeded its restart budget (%s)"
                % (w, self._pool.restart_policy.max_attempts)
            )))
