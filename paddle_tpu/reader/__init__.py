"""Reader-creator decorators (reference python/paddle/reader/decorator.py:
map_readers, shuffle, chain, compose, buffered, firstn, xmap_readers, cache).

A "reader creator" is a zero-arg callable returning a generator of samples —
the same composable protocol the reference trains everything through.
"""

import itertools
import random
import threading
import queue as Queue

from . import creator  # noqa: F401 — np_array/text_file/recordio creators

__all__ = [
    "map_readers",
    "buffered",
    "compose",
    "chain",
    "shuffle",
    "firstn",
    "xmap_readers",
    "cache",
]


def cache(reader):
    all_data = []

    def creator():
        if not all_data:
            all_data.extend(reader())
        return iter(all_data)

    return creator


def map_readers(func, *readers):
    def creator():
        rs = [r() for r in readers]
        for items in zip(*rs):
            yield func(*items)

    return creator


def shuffle(reader, buf_size):
    def creator():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if buf:
            random.shuffle(buf)
            for b in buf:
                yield b

    return creator


def chain(*readers):
    def creator():
        return itertools.chain(*[r() for r in readers])

    return creator


def compose(*readers, **kwargs):
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def creator():
        rs = [r() for r in readers]
        if check_alignment:
            for items in zip(*rs):
                yield sum((make_tuple(i) for i in items), ())
        else:
            for items in itertools.zip_longest(*rs):
                yield sum((make_tuple(i) for i in items if i is not None), ())

    return creator


def buffered(reader, size):
    """Background-thread prefetch buffer (reference decorator.py buffered)."""

    class _End:
        pass

    def creator():
        q = Queue.Queue(maxsize=size)

        def fill():
            try:
                for d in reader():
                    q.put(d)
            finally:
                q.put(_End)

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is _End:
                break
            yield e

    return creator


def firstn(reader, n):
    def creator():
        for i, item in enumerate(reader()):
            if i >= n:
                break
            yield item

    return creator


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over samples with worker threads (reference
    decorator.py xmap_readers). order=True preserves input order via
    sequence-numbered samples and a reordering buffer."""

    end = object()

    def creator():
        in_q = Queue.Queue(buffer_size)
        out_q = Queue.Queue(buffer_size)

        def read_worker():
            for i, sample in enumerate(reader()):
                in_q.put((i, sample))
            for _ in range(process_num):
                in_q.put(end)

        def map_worker():
            while True:
                s = in_q.get()
                if s is end:
                    out_q.put(end)
                    break
                i, sample = s
                out_q.put((i, mapper(sample)))

        threading.Thread(target=read_worker, daemon=True).start()
        workers = [
            threading.Thread(target=map_worker, daemon=True)
            for _ in range(process_num)
        ]
        for w in workers:
            w.start()
        finished = 0
        pending = {}
        next_idx = 0
        while finished < process_num:
            s = out_q.get()
            if s is end:
                finished += 1
                continue
            i, mapped = s
            if not order:
                yield mapped
                continue
            pending[i] = mapped
            while next_idx in pending:
                yield pending.pop(next_idx)
                next_idx += 1
        if order:
            for i in sorted(pending):
                yield pending[i]

    return creator
