"""Reader creators over concrete storage (reference
python/paddle/reader/creator.py: np_array, text_file, recordio) plus the
RecordIO converter (reference python/paddle/fluid/recordio_writer.py +
benchmark/fluid/recordio_converter.py). Records are pickled sample tuples in
native RecordIO chunks (paddle_tpu/native — C++ scanner/writer, CRC +
compression), so a converted dataset feeds training without re-running the
Python preprocessing chain."""

import pickle

from .. import native

__all__ = [
    "np_array",
    "text_file",
    "recordio",
    "convert_reader_to_recordio_file",
    "convert_reader_to_recordio_files",
]


def np_array(x):
    """Yield rows of a numpy array (reference creator.py:np_array)."""

    def reader():
        for row in x:
            yield row

    return reader


def text_file(path):
    """Yield lines without the trailing newline (creator.py:text_file)."""

    def reader():
        with open(path) as f:
            for line in f:
                yield line.rstrip("\n")

    return reader


def recordio(paths, begin=0, end=-1):
    """Yield unpickled samples from native RecordIO file(s); `begin`/`end`
    byte-range shards a single file across trainers (chunk-granular, the Go
    master's task model — native.chunk_offsets gives the cut points)."""
    if isinstance(paths, str):
        paths = paths.split(",")

    def reader():
        for path in paths:
            with native.RecordIOScanner(path, begin, end) as s:
                for rec in s:
                    yield pickle.loads(rec)

    return reader


def convert_reader_to_recordio_file(
    filename,
    reader_creator,
    compressor=native.ZLIB,
    max_num_records=1000,
):
    """Serialize every sample of a reader into one RecordIO file; returns the
    record count (reference recordio_writer.py:convert_reader_to_recordio_file)."""
    count = 0
    with native.RecordIOWriter(
        filename, compressor=compressor, max_records=max_num_records
    ) as w:
        for sample in reader_creator():
            w.write(pickle.dumps(sample, protocol=pickle.HIGHEST_PROTOCOL))
            count += 1
    return count


def convert_reader_to_recordio_files(
    filename,
    batch_per_file,
    reader_creator,
    compressor=native.ZLIB,
    max_num_records=1000,
):
    """Spill a reader into multiple suffixed RecordIO files of
    `batch_per_file` records each (recordio_writer.py:72) — the unit the
    distributed master dispatches."""
    f_name, f_ext = (filename.rsplit(".", 1) + [""])[:2]
    lines = []
    files = []
    idx = 0
    for sample in reader_creator():
        lines.append(sample)
        if len(lines) == batch_per_file:
            path = "%s-%05d%s" % (f_name, idx, "." + f_ext if f_ext else "")
            convert_reader_to_recordio_file(
                path, np_array(lines), compressor, max_num_records
            )
            files.append(path)
            idx += 1
            lines = []
    if lines:
        path = "%s-%05d%s" % (f_name, idx, "." + f_ext if f_ext else "")
        convert_reader_to_recordio_file(
            path, np_array(lines), compressor, max_num_records
        )
        files.append(path)
    return files
