"""Checkpoint and inference-model I/O (reference python/paddle/fluid/io.py:
save/load_vars:89, save/load_params, save/load_persistables:270/490,
save/load_inference_model:570/703).

Design deviation from the reference (documented): the reference serializes
tensors via save/load *ops* (operators/save_op.cc, load_op.cc) executed inside
programs. Side-effectful file ops don't belong inside an XLA module, so here
save/load are host-side executor-level operations reading/writing the Scope —
the user-visible API and on-disk completeness are the same. Tensors are stored
as .npy (one file per var) or a single .npz (`filename=` form, the reference's
save_combine), and the program as JSON (`__model__`, the ProgramDesc analog).
"""

import hashlib
import json
import os

import numpy as np

from . import framework
from .executor import global_scope
from .framework import Parameter, Program, Variable

__all__ = [
    "save_vars",
    "save_params",
    "save_persistables",
    "load_vars",
    "load_params",
    "load_persistables",
    "save_inference_model",
    "load_inference_model",
    "get_inference_program",
    "inference_model_fingerprint",
]

MODEL_FILENAME = "__model__"


def fsync_dir(path):
    """Durably record a directory's entries. os.replace makes a rename
    atomic, but not DURABLE: until the parent directory's metadata hits
    disk, a power cut can roll the rename back — leaving a checkpoint whose
    manifest names files that no longer exist. Checkpoint writers call this
    after renames and before publishing a manifest. Best-effort on
    filesystems that refuse O_RDONLY directory opens."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _bf16_safe_save(arr):
    a = np.asarray(arr)
    if a.dtype.name == "bfloat16" or "bfloat16" in str(a.dtype):
        return a.astype(np.float32), "bfloat16"
    return a, None


def save_arrays(dirname, arrays):
    """bf16-safe per-var np.save of a name->array dict, with the layout
    load_vars reads (`<name>.npy` + per-array `<name>.npy.dtype` sidecars).
    Shared with the pserver checkpoint handler
    (distributed/listen_and_serv.py) so shard checkpoints are restorable by
    the normal loaders."""
    from .resilience import faults as _faults

    os.makedirs(dirname, exist_ok=True)
    # crash-point decision drawn ONCE per save call (so a fault plan's
    # `ckpt_crash:step=N` counts whole checkpoints, not files); it fires
    # below between the first tmp write and its rename — the torn state
    # load_latest_valid must skip
    crash_now = _faults.fires("ckpt_crash")
    dirs_touched = set()
    for name, val in arrays.items():
        arr, orig_dtype = _bf16_safe_save(val)
        path = os.path.join(dirname, name + ".npy")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        # atomic write-then-rename: concurrent checkpointers may legally
        # write the same file (two pserver shards of one cluster checkpoint
        # both record shared vars like the lr); a torn np.save would
        # corrupt the restore of a LATER run, so each writer lands a whole
        # file and os.replace picks a winner (np.save on an open file
        # object appends no suffix)
        tmp = "%s.tmp.%d" % (path, os.getpid())
        with open(tmp, "wb") as f:
            np.save(f, arr)
            # data durability BEFORE the rename: a crash after os.replace
            # but before writeback would otherwise surface a correctly-named
            # file of garbage — exactly what a manifest checksum can't fix
            # once the manifest itself committed over it
            f.flush()
            os.fsync(f.fileno())
        if crash_now:
            # injected mid-commit death: the tmp exists, the rename never
            # happens — exactly the window a real crash hits
            raise _faults.InjectedFault("ckpt_crash during save of %r" % path)
        os.replace(tmp, path)
        # the dtype record travels WITH the array as a sidecar, so a later
        # run reusing the directory can never resurrect a stale record (a
        # shared or per-writer meta file outlives the save that wrote it:
        # an f32 re-save of a var a previous run stored as bf16 would
        # restore silently down-cast). Writers of the same var race only
        # per-var and in the same direction as the .npy itself.
        side = path + ".dtype"
        tmp = "%s.tmp.%d" % (side, os.getpid())
        with open(tmp, "w") as f:
            f.write(orig_dtype or "")  # empty = native dtype, and the
            # sidecar's presence shadows any legacy __dtypes__*.json entry
            # a previous run left for this name
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, side)
        dirs_touched.add(os.path.dirname(path))
    # one dir fsync per directory, after all renames: the renames become
    # durable together, and a manifest published after save_arrays returns
    # can never name a file a power cut un-renames
    for d in sorted(dirs_touched):
        fsync_dir(d)


def _load_dtype_meta(dirname):
    """Merge every legacy `__dtypes__*.json` in dirname into a name->dtype
    map. Current saves record dtypes as per-array `<name>.npy.dtype`
    sidecars (checked first by _stored_dtype); the merged metas remain
    readable for checkpoints written by earlier layouts."""
    meta = {}
    try:
        names = sorted(os.listdir(dirname))
    except OSError:
        return meta
    for fname in names:
        if fname.startswith("__dtypes__") and fname.endswith(".json"):
            try:
                with open(os.path.join(dirname, fname)) as f:
                    meta.update(json.load(f))
            except (OSError, ValueError):
                # a torn legacy meta (writer died mid-dump) must not fail
                # the load; the per-array sidecars still carry the dtypes
                # for anything saved by the current layout
                continue
    return meta


def _stored_dtype(dirname, name, meta):
    """Recorded save-dtype for `<dirname>/<name>.npy`: the sidecar wins
    (written/removed atomically beside the array), legacy metas otherwise."""
    side = os.path.join(dirname, name + ".npy.dtype")
    try:
        with open(side) as f:
            return f.read().strip() or None
    except OSError:
        return meta.get(name)


def load_arrays(dirname):
    """Inverse of save_arrays: read every `<name>.npy` in dirname back into a
    name->array dict (bf16 restored per the `__dtypes__*.json` metas). Used
    by pserver shard-checkpoint restore (a pserver's shard var names are only
    known to the transpiled program, so restore is by-directory, not
    by-program)."""
    import jax.numpy as jnp

    meta = _load_dtype_meta(dirname)
    out = {}
    for root, _dirs, files in os.walk(dirname):
        for fname in sorted(files):
            if not fname.endswith(".npy") or ".tmp." in fname:
                continue  # skip orphaned atomic-write temps
            path = os.path.join(root, fname)
            # var names may contain path separators (save_arrays makes the
            # subdirs); reconstruct the name relative to dirname
            name = os.path.relpath(path, dirname)[: -len(".npy")]
            arr = np.load(path)
            if _stored_dtype(dirname, name, meta) == "bfloat16":
                arr = jnp.asarray(arr, dtype=jnp.bfloat16)
            out[name] = arr
    return out


def save_vars(
    executor,
    dirname,
    main_program=None,
    vars=None,
    predicate=None,
    filename=None,
):
    """Persist selected scope variables (reference io.py:89 save_vars)."""
    program = main_program or framework.default_main_program()
    if vars is None:
        vars = [v for v in program.list_vars() if predicate is None or predicate(v)]
    scope = global_scope()
    os.makedirs(dirname, exist_ok=True)
    arrays = {}
    for v in vars:
        name = v.name if isinstance(v, Variable) else str(v)
        val = scope.find_var(name)
        if val is None:
            raise RuntimeError("variable %r has no value in scope; run startup first" % name)
        arrays[name] = val
    if filename is None:
        save_arrays(dirname, arrays)
    else:
        combined = {}
        meta = {}
        for name, val in arrays.items():
            arr, orig_dtype = _bf16_safe_save(val)
            if orig_dtype:
                meta[name] = orig_dtype
            combined[name] = arr
        np.savez(os.path.join(dirname, filename), **combined)
        # always rewrite (even empty): an earlier save's meta left in place
        # would apply stale dtypes to a later all-f32 save of the same file.
        # Atomic like save_arrays' payloads: a crash mid-write must not leave
        # a torn half-JSON that poisons every later load of the directory
        path = os.path.join(dirname, "__dtypes__.json")
        tmp = "%s.tmp.%d" % (path, os.getpid())
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, path)


def _is_param(v):
    return isinstance(v, Parameter)


def _is_persistable(v):
    return v.persistable and v.type not in (
        framework.VarType.RAW,
        framework.VarType.READER,
    )


def save_params(executor, dirname, main_program=None, filename=None):
    return save_vars(
        executor, dirname, main_program, predicate=_is_param, filename=filename
    )


def save_persistables(executor, dirname, main_program=None, filename=None):
    return save_vars(
        executor, dirname, main_program, predicate=_is_persistable, filename=filename
    )


def load_vars(
    executor,
    dirname,
    main_program=None,
    vars=None,
    predicate=None,
    filename=None,
):
    import jax.numpy as jnp

    program = main_program or framework.default_main_program()
    if vars is None:
        vars = [v for v in program.list_vars() if predicate is None or predicate(v)]
    scope = global_scope()
    combined = None
    if filename is not None:
        combined = np.load(os.path.join(dirname, filename + (".npz" if not filename.endswith(".npz") else "")))
        # the combined save co-writes exactly __dtypes__.json (always, even
        # empty); merging stray per-PID metas from an earlier per-var run
        # here would resurrect stale dtype records
        try:
            with open(os.path.join(dirname, "__dtypes__.json")) as f:
                meta = json.load(f)
        except (OSError, ValueError):
            # missing OR torn (a legacy writer died mid-json.dump): degrade
            # to no dtype records — bf16 vars restore as their f32 payloads
            # — rather than failing the whole load over a sidecar
            meta = {}
    else:
        meta = _load_dtype_meta(dirname)
    for v in vars:
        name = v.name if isinstance(v, Variable) else str(v)
        if combined is not None:
            arr = combined[name]
            if meta.get(name) == "bfloat16":
                arr = jnp.asarray(arr, dtype=jnp.bfloat16)
        else:
            arr = np.load(os.path.join(dirname, name + ".npy"))
            if _stored_dtype(dirname, name, meta) == "bfloat16":
                arr = jnp.asarray(arr, dtype=jnp.bfloat16)
        # jnp.array (copy), not asarray: a zero-copy wrap of the loaded numpy
        # buffer corrupts same-sized params once the donating step jit runs
        # (see resilience/elastic.py Supervisor._overlay)
        scope.set_var(name, jnp.array(arr))


def load_params(executor, dirname, main_program=None, filename=None):
    return load_vars(
        executor, dirname, main_program, predicate=_is_param, filename=filename
    )


def load_persistables(executor, dirname, main_program=None, filename=None):
    return load_vars(
        executor, dirname, main_program, predicate=_is_persistable, filename=filename
    )


def get_inference_program(target_vars, main_program=None):
    program = main_program or framework.default_main_program()
    if not isinstance(target_vars, (list, tuple)):
        target_vars = [target_vars]
    pruned = program.clone(for_test=True)._prune(target_vars)
    return pruned


def save_inference_model(
    dirname,
    feeded_var_names,
    target_vars,
    executor,
    main_program=None,
    model_filename=None,
    params_filename=None,
    export_for_deployment=True,
):
    """Prune to targets + save program and params (reference io.py:570).
    The saved `__model__` JSON also records feed/fetch names (the reference
    encodes them as feed/fetch ops prepended/appended to the program)."""
    program = main_program or framework.default_main_program()
    if not isinstance(target_vars, (list, tuple)):
        target_vars = [target_vars]
    pruned = program.clone(for_test=True)._prune(target_vars)
    os.makedirs(dirname, exist_ok=True)
    doc = pruned.to_dict()
    doc["feed_var_names"] = list(feeded_var_names)
    doc["fetch_var_names"] = [
        t.name if isinstance(t, Variable) else str(t) for t in target_vars
    ]
    with open(os.path.join(dirname, model_filename or MODEL_FILENAME), "w") as f:
        json.dump(doc, f)
    # only persistables the pruned program still references
    needed = {
        v.name
        for v in pruned.list_vars()
        if v.persistable
    }
    save_vars(
        executor,
        dirname,
        program,
        vars=[v for v in program.list_vars() if v.persistable and v.name in needed],
        filename=params_filename,
    )
    return doc["fetch_var_names"]


def inference_model_fingerprint(dirname, model_filename=None):
    """Stable sha256 over a saved inference model's PROGRAM plus the
    parameters' STORED dtypes — the serving compile-cache identity
    (serving/compile_cache.py).

    Deliberately excludes parameter VALUES: compiled serving artifacts take
    parameters as call arguments, so retrained weights of the same
    shapes/dtypes reuse every cached executable (the whole point of a
    persistent cache across model pushes). Shapes and compute dtypes ride
    the program JSON; the per-var `.npy.dtype` sidecars (and legacy
    `__dtypes__*.json` metas) are folded in because a bf16-stored parameter
    loads as bf16 and changes the traced avals without touching the
    program."""
    h = hashlib.sha256()
    with open(os.path.join(dirname, model_filename or MODEL_FILENAME), "rb") as f:
        h.update(f.read())
    meta = _load_dtype_meta(dirname)
    with open(os.path.join(dirname, model_filename or MODEL_FILENAME)) as f:
        doc = json.load(f)
    program = Program.from_dict(doc)
    for v in sorted(
        (v for v in program.list_vars() if v.persistable), key=lambda v: v.name
    ):
        stored = _stored_dtype(dirname, v.name, meta)
        h.update(("%s\x00%s\n" % (v.name, stored or "")).encode())
    return h.hexdigest()


def load_inference_model(
    dirname, executor, model_filename=None, params_filename=None
):
    """Returns (program, feed_var_names, fetch_vars) like the reference
    (io.py:703)."""
    with open(os.path.join(dirname, model_filename or MODEL_FILENAME)) as f:
        doc = json.load(f)
    program = Program.from_dict(doc)
    load_vars(
        executor,
        dirname,
        program,
        vars=[v for v in program.list_vars() if v.persistable],
        filename=params_filename,
    )
    fetch_vars = [
        program.global_block().var(n) for n in doc.get("fetch_var_names", [])
    ]
    return program, doc.get("feed_var_names", []), fetch_vars
