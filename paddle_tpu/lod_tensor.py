"""LoDTensor construction helpers (reference python/paddle/fluid/
lod_tensor.py: create_lod_tensor / create_random_int_lodtensor).

LoD redesign (SURVEY.md §5.7): ragged batches ride as padded dense arrays +
an explicit sequence-length vector instead of offset tables, so the helpers
return (padded_array, seq_len) pairs — the exact convention the sequence ops
and DataFeeder consume."""

import numpy as np

__all__ = ["create_lod_tensor", "create_random_int_lodtensor", "to_dlpack", "from_dlpack"]


def create_lod_tensor(data, recursive_seq_lens, place=None):
    """Build a padded batch from per-sequence rows.

    `data`: list of per-sequence numpy arrays/lists, or a flat (sum_len, d)
    array partitioned by `recursive_seq_lens` (one level, like the reference's
    common case). Returns (padded [B, T, ...], seq_len [B]) — LoD level 1."""
    if isinstance(recursive_seq_lens[0], (list, tuple)):
        if len(recursive_seq_lens) != 1:
            raise ValueError(
                "padded-dense LoD supports one recursion level "
                "(deeper nesting is a reshape away for every reference use)"
            )
        seq_lens = list(recursive_seq_lens[0])
    else:
        seq_lens = list(recursive_seq_lens)

    if isinstance(data, (list, tuple)):
        rows = [np.asarray(d) for d in data]
    else:
        flat = np.asarray(data)
        rows = []
        ofs = 0
        for n in seq_lens:
            rows.append(flat[ofs : ofs + n])
            ofs += n
    if len(rows) != len(seq_lens):
        raise ValueError("data has %d sequences but lens has %d" % (len(rows), len(seq_lens)))
    t = max(seq_lens) if seq_lens else 0
    tail = rows[0].shape[1:] if rows and rows[0].ndim > 1 else ()
    out = np.zeros((len(rows), t) + tuple(tail), rows[0].dtype if rows else np.float32)
    for i, (r, n) in enumerate(zip(rows, seq_lens)):
        out[i, :n] = np.asarray(r).reshape((n,) + tuple(tail))
    return out, np.asarray(seq_lens, np.int64)


def create_random_int_lodtensor(recursive_seq_lens, base_shape, place, low, high):
    lens = (
        recursive_seq_lens[0]
        if isinstance(recursive_seq_lens[0], (list, tuple))
        else recursive_seq_lens
    )
    rows = [
        np.random.randint(low, high + 1, size=(n,) + tuple(base_shape))
        for n in lens
    ]
    return create_lod_tensor(rows, [list(lens)], place)


def to_dlpack(value):
    """DLPack-capable view of a framework value (reference
    framework/dlpack_tensor.cc — tensor interop with other frameworks).
    Modern DLPack is object-protocol based: the returned object implements
    __dlpack__/__dlpack_device__ and is consumed directly by
    torch.utils.dlpack.from_dlpack / np.from_dlpack. CPU/GPU buffers
    exchange zero-copy; TPU HBM is not DLPack-addressable, so TPU-resident
    values are staged to host first (one copy, unavoidable by protocol)."""
    import jax
    import jax.numpy as jnp

    arr = value if isinstance(value, jax.Array) else jnp.asarray(value)
    try:
        arr.__dlpack_device__()
    except Exception:
        return np.asarray(arr)  # host staging for non-DLPack devices (TPU)
    return arr


def from_dlpack(tensor):
    """Import a DLPack-capable tensor (torch/numpy/another framework's) as
    a framework (jax) array — zero-copy where the protocol allows."""
    import jax.numpy as jnp

    return jnp.from_dlpack(tensor)
