"""Model-zoo benchmark launcher.

Reference analog: benchmark/fluid/fluid_benchmark.py + args.py — a CLI that
builds one of the zoo models, optionally dist-transpiles by env role, trains
`--pass_num` passes of `--iterations` minibatches, and prints per-pass
throughput. The TPU-native edition keeps the surface (models, fake-data mode,
infer_only, memory_optimize, profile, pserver env-role mode) and replaces the
nccl2 update method with `spmd` (ParallelExecutor over the device mesh).

Usage:
    python benchmark/fluid_benchmark.py --model resnet --device TPU \
        --batch_size 64 --iterations 20 --pass_num 2 --use_bf16
Env-role pserver mode (reference dist env contract):
    PADDLE_TRAINING_ROLE=PSERVER|TRAINER PADDLE_PSERVER_IPS=... \
    PADDLE_TRAINERS=2 PADDLE_TRAINER_ID=0 python benchmark/fluid_benchmark.py \
        --model mnist --update_method pserver
"""

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import paddle_tpu.fluid as fluid  # noqa: E402
from paddle_tpu import framework  # noqa: E402
from paddle_tpu.executor import Scope, scope_guard  # noqa: E402


def parse_args(argv=None):
    p = argparse.ArgumentParser("fluid-style model benchmark")
    p.add_argument("--model", default="mnist",
                   choices=["mnist", "resnet", "vgg", "stacked_dynamic_lstm",
                            "machine_translation", "transformer"])
    p.add_argument("--batch_size", type=int, default=32)
    p.add_argument("--learning_rate", type=float, default=0.001)
    p.add_argument("--skip_batch_num", type=int, default=5)
    p.add_argument("--iterations", type=int, default=30)
    p.add_argument("--pass_num", type=int, default=1)
    p.add_argument("--device", default="TPU", choices=["CPU", "TPU"])
    p.add_argument("--data_set", default="flowers",
                   choices=["cifar10", "flowers", "imagenet"])
    p.add_argument("--infer_only", action="store_true")
    p.add_argument("--use_fake_data", action="store_true", default=True,
                   help="synthetic batches staged once (reference fake-data mode)")
    p.add_argument("--memory_optimize", action="store_true")
    p.add_argument("--use_bf16", action="store_true",
                   help="bf16 training (the fp16/data_format analog on TPU)")
    p.add_argument("--profile", action="store_true")
    p.add_argument("--update_method", default="local",
                   choices=["local", "pserver", "spmd"])
    p.add_argument("--async_mode", action="store_true")
    p.add_argument("--no_split_var", action="store_true")
    return p.parse_args(argv)


# --------------------------------------------------------------------------
# model adapters: build(main, startup, args) -> (loss, feed_fn)
# --------------------------------------------------------------------------


def _img_label(shape, classes):
    img = fluid.layers.data(name="img", shape=list(shape), dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    return img, label


def _img_feed(args, shape, classes):
    rng = np.random.RandomState(0)
    return {
        "img": rng.randn(args.batch_size, *shape).astype("float32"),
        "label": rng.randint(0, classes, (args.batch_size, 1)).astype("int64"),
    }


def build_mnist(args):
    from paddle_tpu.models import lenet

    img, label = _img_label((1, 28, 28), 10)
    loss, acc, _ = lenet.lenet5(img, label)
    return loss, lambda: _img_feed(args, (1, 28, 28), 10)


def build_resnet(args):
    from paddle_tpu.models import resnet

    if args.data_set == "cifar10":
        img, label = _img_label((3, 32, 32), 10)
        loss, acc, _ = resnet.resnet_cifar10(img, label)
        return loss, lambda: _img_feed(args, (3, 32, 32), 10)
    img, label = _img_label((3, 224, 224), 1000)
    loss, acc, _ = resnet.resnet50(img, label)
    return loss, lambda: _img_feed(args, (3, 224, 224), 1000)


def build_vgg(args):
    from paddle_tpu.models import vgg

    shape, classes = ((3, 32, 32), 10) if args.data_set == "cifar10" else (
        (3, 224, 224), 1000)
    img, label = _img_label(shape, classes)
    loss, acc, _ = vgg.vgg16(img, label, class_num=classes)
    return loss, lambda: _img_feed(args, shape, classes)


def build_stacked_dynamic_lstm(args, dict_dim=30000, t=100):
    from paddle_tpu.models.stacked_lstm import stacked_lstm_net

    words = fluid.layers.data(name="words", shape=[1], dtype="int64", lod_level=1)
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    loss, acc, _ = stacked_lstm_net(
        words, label, dict_dim=dict_dim, emb_dim=512, hid_dim=512, stacked_num=2
    )
    rng = np.random.RandomState(0)

    def feed():
        return {
            "words": rng.randint(0, dict_dim, (args.batch_size, t, 1)).astype("int64"),
            "words@LEN": np.full((args.batch_size,), t, "int32"),
            "label": rng.randint(0, 2, (args.batch_size, 1)).astype("int64"),
        }

    return loss, feed


def build_machine_translation(args, dict_size=10000, t=16):
    from paddle_tpu.models import machine_translation as mt

    b = args.batch_size
    # the attention mask needs static (B, T) shapes; lengths ride explicit
    # companion vars (the tests/test_machine_translation.py declaration)
    src = fluid.layers.data(name="src_word", shape=[b, t, 1], dtype="int64",
                            append_batch_size=False)
    fluid.framework.default_main_program().global_block().create_var(
        name="src_len", shape=(b,), dtype="int64")
    src._len_name = "src_len"
    trg = fluid.layers.data(name="trg_word", shape=[b, t + 1, 1], dtype="int64",
                            append_batch_size=False)
    lbl = fluid.layers.data(name="label", shape=[b, t + 1, 1], dtype="int64",
                            append_batch_size=False)
    tlen = fluid.layers.data(name="trg_len", shape=[b], dtype="int64",
                             append_batch_size=False)
    loss = mt.train_model(src, trg, lbl, tlen, dict_size)
    rng = np.random.RandomState(0)

    def feed():
        return {
            "src_word": rng.randint(0, dict_size, (b, t, 1)).astype("int64"),
            "src_len": np.full((b,), t, "int64"),
            "trg_word": rng.randint(0, dict_size, (b, t + 1, 1)).astype("int64"),
            "label": rng.randint(0, dict_size, (b, t + 1, 1)).astype("int64"),
            "trg_len": np.full((b,), t + 1, "int64"),
        }

    return loss, feed


def build_transformer(args, vocab=1000, t=64):
    from paddle_tpu.models import transformer as T

    feeds = {}
    for name, shape, dtype in [
        ("src_word", [t], "int64"), ("src_pos", [t], "int64"),
        ("trg_word", [t], "int64"), ("trg_pos", [t], "int64"),
        ("label", [t], "int64"), ("label_weight", [t, 1], "float32"),
    ]:
        feeds[name] = fluid.layers.data(name=name, shape=shape, dtype=dtype)
    loss, _ = T.transformer(
        feeds["src_word"], feeds["src_pos"], feeds["trg_word"],
        feeds["trg_pos"], None, None, None,
        feeds["label"], feeds["label_weight"],
        src_vocab_size=vocab, trg_vocab_size=vocab,
        n_layer=2, n_head=8, d_model=256, d_inner=1024, d_key=32, d_value=32,
        dropout=0.0, max_length=t + 1, use_flash=True, padded=False,
    )
    rng = np.random.RandomState(0)
    pos = np.tile(np.arange(t), (args.batch_size, 1)).astype("int64")

    def feed():
        b = args.batch_size
        return {
            "src_word": rng.randint(0, vocab, (b, t)).astype("int64"),
            "src_pos": pos,
            "trg_word": rng.randint(0, vocab, (b, t)).astype("int64"),
            "trg_pos": pos.copy(),
            "label": rng.randint(0, vocab, (b, t)).astype("int64"),
            "label_weight": np.ones((b, t, 1), "float32"),
        }

    return loss, feed


_BUILDERS = {
    "mnist": build_mnist,
    "resnet": build_resnet,
    "vgg": build_vgg,
    "stacked_dynamic_lstm": build_stacked_dynamic_lstm,
    "machine_translation": build_machine_translation,
    "transformer": build_transformer,
}


def dist_transpile(args, train_prog, startup_prog):
    """Env-role pserver transpile (reference fluid_benchmark.py:63 contract:
    PADDLE_PSERVER_IPS/PADDLE_PSERVER_PORT/PADDLE_TRAINERS/PADDLE_TRAINER_ID/
    PADDLE_CURRENT_IP/PADDLE_TRAINING_ROLE)."""
    from paddle_tpu.transpiler import DistributeTranspiler, DistributeTranspilerConfig

    port = os.getenv("PADDLE_PSERVER_PORT", "6174")
    pserver_ips = os.getenv("PADDLE_PSERVER_IPS", "")
    eplist = [":".join([ip, port]) for ip in pserver_ips.split(",") if ip]
    pserver_endpoints = ",".join(eplist)
    trainers = int(os.getenv("PADDLE_TRAINERS", "1"))
    trainer_id = int(os.getenv("PADDLE_TRAINER_ID", "0"))
    current_endpoint = os.getenv("PADDLE_CURRENT_IP", "127.0.0.1") + ":" + port
    role = os.getenv("PADDLE_TRAINING_ROLE", "TRAINER")

    config = DistributeTranspilerConfig()
    config.slice_var_up = not args.no_split_var
    t = DistributeTranspiler(config=config)
    t.transpile(
        trainer_id, program=train_prog, pservers=pserver_endpoints,
        trainers=trainers, sync_mode=not args.async_mode,
        startup_program=startup_prog,
    )
    if role == "PSERVER":
        pserver_program = t.get_pserver_program(current_endpoint)
        pserver_startup = t.get_startup_program(
            current_endpoint, pserver_program, startup_program=startup_prog
        )
        return "pserver", pserver_program, pserver_startup
    return "trainer", t.get_trainer_program(), startup_prog


def main(argv=None):
    args = parse_args(argv)
    main_prog, startup_prog = framework.Program(), framework.Program()
    with fluid.unique_name.guard():
        with fluid.program_guard(main_prog, startup_prog):
            loss, feed_fn = _BUILDERS[args.model](args)
            if not args.infer_only:
                fluid.optimizer.Adam(learning_rate=args.learning_rate).minimize(loss)
            elif hasattr(main_prog, "clone"):
                main_prog = main_prog.clone(for_test=True)

    if args.memory_optimize:
        fluid.memory_optimize(main_prog)

    place = fluid.CPUPlace() if args.device == "CPU" else fluid.TPUPlace()
    exe = fluid.Executor(place)

    if args.update_method == "pserver":
        role, prog, startup = dist_transpile(args, main_prog, startup_prog)
        if role == "pserver":
            with scope_guard(Scope(seed=0)):
                exe.run(startup)
                exe.run(prog)  # serves until trainers disconnect
            return []
        main_prog = prog

    results = []
    with scope_guard(Scope(seed=0)):
        exe.run(startup_prog)
        if args.use_bf16:
            from paddle_tpu.transpiler.bf16_transpiler import Bf16Transpiler

            Bf16Transpiler().transpile(main_prog)

        runner = exe
        run_kw = {}
        if args.update_method == "spmd":
            pe = fluid.ParallelExecutor(
                use_cuda=False, loss_name=loss.name, main_program=main_prog
            )
            runner = pe

        feed = feed_fn()
        import jax

        feed = {k: jax.device_put(v) for k, v in feed.items()}
        fetch = [loss.name]

        def run_once():
            if runner is exe:
                return exe.run(main_prog, feed=feed, fetch_list=fetch,
                               return_numpy=False)
            return runner.run(fetch_list=fetch, feed=feed)

        def one_pass(profiling=False):
            out = None
            t_start = None
            n = 0
            maybe_prof = (
                fluid.profiler.profiler("All", "total")
                if profiling
                else _null_ctx()
            )
            with maybe_prof:
                for it in range(args.iterations):
                    if it == args.skip_batch_num:
                        if out is not None:
                            np.asarray(out[0])  # sync warmup before timing
                        t_start = time.time()
                        n = 0
                    out = run_once()
                    n += args.batch_size
            last = float(np.asarray(out[0]).reshape(-1)[0])  # syncs the pass
            dt = time.time() - (t_start or time.time())
            return (n / dt if dt > 0 else float("nan")), last

        for pass_id in range(args.pass_num):
            ips, last_loss = one_pass(profiling=args.profile and pass_id == 0)
            results.append(ips)
            print("Pass: %d, Throughput: %.2f samples/s, Loss: %s"
                  % (pass_id, ips, last_loss))
    return results


class _null_ctx(object):
    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


if __name__ == "__main__":
    main()
