#!/usr/bin/env python
"""Print the public API surface as `module.name (signature)` lines.

Reference analog: tools/print_signatures.py + tools/diff_api.py — the
API-stability gate: CI regenerates the spec and diffs it against the
committed paddle_tpu/API.spec; an unreviewed surface change fails the build
(tests/test_api_spec.py is the gate here).

Usage: python tools/print_signatures.py > paddle_tpu/API.spec
"""

import inspect
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")


MODULES = [
    "paddle_tpu.fluid",
    "paddle_tpu.layers",
    "paddle_tpu.layers.control_flow",
    "paddle_tpu.layers.detection",
    "paddle_tpu.layers.io",
    "paddle_tpu.optimizer",
    "paddle_tpu.initializer",
    "paddle_tpu.io",
    "paddle_tpu.metrics",
    "paddle_tpu.nets",
    "paddle_tpu.clip",
    "paddle_tpu.regularizer",
    "paddle_tpu.profiler",
    "paddle_tpu.transpiler",
    "paddle_tpu.passes",
    "paddle_tpu.analysis",
    "paddle_tpu.reader",
    "paddle_tpu.reader.creator",
    "paddle_tpu.imperative",
    "paddle_tpu.average",
    "paddle_tpu.backward",
    "paddle_tpu.data_feed_desc",
    "paddle_tpu.async_executor",
    "paddle_tpu.lod_tensor",
    "paddle_tpu.inference",
    "paddle_tpu.serving",
    "paddle_tpu.fleet",
    "paddle_tpu.data",
    "paddle_tpu.embedding",
    "paddle_tpu.online",
    "paddle_tpu.observability",
    "paddle_tpu.resilience",
    "paddle_tpu.contrib",
    "paddle_tpu.contrib.memory_usage_calc",
]


def _sig(obj):
    try:
        return str(inspect.signature(obj))
    except (TypeError, ValueError):
        return "(...)"


def collect():
    import importlib

    lines = []
    for modname in MODULES:
        mod = importlib.import_module(modname)
        names = getattr(mod, "__all__", None)
        if names is None:
            names = [n for n in dir(mod) if not n.startswith("_")]
        for name in sorted(set(names)):
            obj = getattr(mod, name, None)
            if obj is None or inspect.ismodule(obj):
                continue
            if inspect.isclass(obj):
                lines.append("%s.%s.__init__ %s" % (modname, name, _sig(obj.__init__)))
                for mname, m in sorted(inspect.getmembers(obj, inspect.isfunction)):
                    if not mname.startswith("_"):
                        lines.append("%s.%s.%s %s" % (modname, name, mname, _sig(m)))
            elif callable(obj):
                lines.append("%s.%s %s" % (modname, name, _sig(obj)))
    return lines


if __name__ == "__main__":
    # direct script invocation runs from tools/: make the repo importable
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    print("\n".join(collect()))
