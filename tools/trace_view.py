#!/usr/bin/env python
"""Terminal viewer for distributed request traces (FLAGS_trace_dir shards).

Two modes over the same span shards (observability/tracing.py), mirroring
what the reference stack reads off its Jaeger UI:

- default: a top-k table of the slowest traces — root name, duration,
  span/process counts, status — the "what should I look at" ranking;
- --trace <id>: the full span tree of one trace, siblings in start order,
  with the critical path (the chain of last-finishing spans from the root)
  marked `*` — the "where did the time go" drilldown. Works across
  processes: spans from every shard in the directory join one tree.

Usage:
  python tools/trace_view.py /tmp/traces                 # top-k slowest
  python tools/trace_view.py /tmp/traces --top 20
  python tools/trace_view.py /tmp/traces --errors        # error traces only
  python tools/trace_view.py /tmp/traces --trace a1b2... # one trace's tree
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddle_tpu.observability import tracing as _tracing  # noqa: E402


def group_traces(spans):
    """trace_id -> list of span records, span-kind records only."""
    traces = {}
    for s in spans:
        if s.get("kind") != "span" or not s.get("trace"):
            continue
        traces.setdefault(s["trace"], []).append(s)
    return traces


def trace_summary(trace_id, spans):
    by_id = {s["span"]: s for s in spans}
    roots = [s for s in spans if not s.get("parent")
             or s["parent"] not in by_id]
    # duration: first start to last end across the whole trace — a root
    # whose children outlive it (async hand-off) still counts fully
    t0 = min(s["ts"] for s in spans)
    t1 = max(s["ts"] + s.get("dur_ms", 0.0) / 1e3 for s in spans)
    root = min(roots, key=lambda s: s["ts"]) if roots else spans[0]
    return {
        "trace": trace_id,
        "root": root.get("name", "?"),
        "dur_ms": (t1 - t0) * 1e3,
        "spans": len(spans),
        "procs": len({(s.get("host"), s.get("pid")) for s in spans}),
        "errors": sum(1 for s in spans if s.get("status") == "error"),
        "ts": t0,
    }


def critical_path(spans):
    """Span ids on the chain of last-finishing spans from the earliest
    root: at each node descend into the child whose end time is latest.
    That chain is what bounded the trace's wall time."""
    by_id = {s["span"]: s for s in spans}
    children = {}
    for s in spans:
        p = s.get("parent")
        if p in by_id:
            children.setdefault(p, []).append(s)
    roots = [s for s in spans if s.get("parent") not in by_id]
    if not roots:
        return set()
    node = min(roots, key=lambda s: s["ts"])
    path = {node["span"]}
    while True:
        kids = children.get(node["span"])
        if not kids:
            return path
        node = max(kids, key=lambda s: s["ts"] + s.get("dur_ms", 0.0) / 1e3)
        path.add(node["span"])


def _fmt_tags(s):
    tags = s.get("tags") or {}
    return " ".join("%s=%s" % (k, tags[k]) for k in sorted(tags))


def render_trace(trace_id, spans, out=sys.stdout):
    by_id = {s["span"]: s for s in spans}
    children = {}
    roots = []
    for s in spans:
        p = s.get("parent")
        if p in by_id:
            children.setdefault(p, []).append(s)
        else:
            roots.append(s)
    crit = critical_path(spans)
    t0 = min(s["ts"] for s in spans)
    out.write("trace %s  (%d spans, %d processes)\n" % (
        trace_id, len(spans),
        len({(s.get("host"), s.get("pid")) for s in spans})))

    def walk(s, depth):
        mark = "*" if s["span"] in crit else " "
        status = "" if s.get("status") == "ok" else " [%s]" % s.get("status")
        out.write("%s %s+%7.1fms %8.1fms  %s%s  (%s:p%s)  %s\n" % (
            mark, "  " * depth, (s["ts"] - t0) * 1e3,
            s.get("dur_ms", 0.0), s.get("name", "?"), status,
            s.get("host", "?"), s.get("pid", "?"), _fmt_tags(s)))
        for ev in s.get("events") or []:
            out.write("  %s  . %s %s\n" % (
                "  " * depth, ev.get("name"),
                " ".join("%s=%s" % (k, v) for k, v in sorted(ev.items())
                         if k not in ("name", "ts"))))
        for c in sorted(children.get(s["span"], ()), key=lambda x: x["ts"]):
            walk(c, depth + 1)

    for r in sorted(roots, key=lambda s: s["ts"]):
        walk(r, 0)
    out.write("* = critical path (chain of last-finishing spans)\n")


def render_top(traces, top=10, errors_only=False, out=sys.stdout):
    rows = [trace_summary(tid, sp) for tid, sp in traces.items()]
    if errors_only:
        rows = [r for r in rows if r["errors"]]
    rows.sort(key=lambda r: -r["dur_ms"])
    out.write("%-16s %-24s %10s %6s %6s %6s\n" % (
        "trace", "root", "dur_ms", "spans", "procs", "errs"))
    for r in rows[:top]:
        out.write("%-16s %-24s %10.1f %6d %6d %6d\n" % (
            r["trace"], r["root"][:24], r["dur_ms"], r["spans"],
            r["procs"], r["errors"]))
    out.write("%d traces total%s\n" % (
        len(rows), ", errors only" if errors_only else ""))
    return rows


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("trace_dir",
                    help="FLAGS_trace_dir directory or one trace-*.jsonl shard")
    ap.add_argument("--top", type=int, default=10,
                    help="how many slowest traces to list")
    ap.add_argument("--errors", action="store_true",
                    help="list only traces containing an error span")
    ap.add_argument("--trace", default="",
                    help="render one trace id's span tree (prefix match)")
    args = ap.parse_args(argv)
    traces = group_traces(_tracing.load_spans(args.trace_dir))
    if not traces:
        print("no spans under %s" % args.trace_dir)
        return 1
    if args.trace:
        hits = [t for t in traces if t.startswith(args.trace)]
        if not hits:
            print("no trace matching %r" % args.trace)
            return 1
        for t in sorted(hits):
            render_trace(t, traces[t])
        return 0
    render_top(traces, top=args.top, errors_only=args.errors)
    return 0


if __name__ == "__main__":
    sys.exit(main())
