"""Input-pipeline bottleneck table (VERDICT r04 item 5 evidence).

Measures each stage of the image input path in isolation on this host +
device pair and writes PIPELINE_KEEPUP.json:

  host_batch_assembly   — np.stack of bs=256 uint8 HWC images -> wire batch
  wire_f32 / wire_uint8 — raw host->device device_put throughput at the two
                          wire formats (the transfer the feeder thread does)
  device_step           — staged-batch ResNet-50 bs=256 train-step rate
  pyreader_uint8        — the full async pipeline (PyReader, uint8 wire)
  cached_epoch          — PyReader(cache_epoch=True) replay rate: epoch 1
                          pays the wire once, later epochs serve staged
                          device arrays (wire out of the loop)

The keep-up verdict is mechanical: if wire_uint8 (bytes/s) cannot carry
batch_bytes x device_step (batches/s), the pipeline is WIRE-bound and no
reader design can close the gap on this link — the evidence the r04 verdict
asked for ("a measured host-side bottleneck table (bytes/s per stage)
proving the residual is hardware, not design"). On a production TPU host
NIC/PCIe the same math applies with its own wire rate.

Reference analog: operators/reader/buffered_reader.h:48 (double buffering
exists to hide exactly this transfer).

Usage: python tools/pipeline_probe.py [--quick]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import jax

    import bench

    # --quick: skip the ResNet-50 stages (device_step, pyreader_uint8) —
    # they need an accelerator-class host; the host/wire/cache stages still
    # run and the device_step rate is carried forward from the last full
    # probe (with provenance recorded in the JSON).
    quick = "--quick" in sys.argv[1:]
    bs = 256
    out_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "PIPELINE_KEEPUP.json",
    )
    prior = {}
    if quick and os.path.exists(out_path):
        with open(out_path) as f:
            prior = json.load(f)
    # carry mode: a prior full probe exists — its host/wire/device-step
    # numbers (measured on the real accelerator host) stay as the
    # first-epoch path; this run only adds the cached-epoch measurement,
    # labelled with the host it ran on.
    carry = quick and "device_step_batches_per_s" in prior
    if carry:
        record = dict(prior)
        record["cached_epoch_measured_on"] = str(jax.devices()[0])
    else:
        record = {"batch_size": bs, "device": str(jax.devices()[0])}

    # stage 1: host batch assembly (decode/stack analog — synthetic pixels)
    imgs = [np.random.randint(0, 256, (3, 224, 224), dtype=np.uint8)
            for _ in range(bs)]
    batch = np.stack(imgs)
    if not carry:
        t0 = time.perf_counter()
        reps = 8
        for _ in range(reps):
            batch = np.stack(imgs)
        dt = (time.perf_counter() - t0) / reps
        record["host_batch_assembly_batches_per_s"] = round(1 / dt, 2)
        record["host_batch_assembly_MBps"] = round(batch.nbytes / dt / 1e6, 1)

    # stage 2: wire throughput per format
    if not carry:
        for name, arr in [
            ("uint8", batch),
            ("f32", batch.astype(np.float32)),
        ]:
            x = jax.device_put(arr)  # warm
            np.asarray(x[0, 0, 0, :2])
            t0 = time.perf_counter()
            n = 2 if name == "f32" else 4
            for _ in range(n):
                x = jax.device_put(arr)
            np.asarray(x[0, 0, 0, :2])
            dt = (time.perf_counter() - t0) / n
            record["wire_%s_MBps" % name] = round(arr.nbytes / dt / 1e6, 1)
            record["wire_%s_batches_per_s" % name] = round(1 / dt, 3)

    # stage 3: device step rate (staged batches, no wire in the loop)
    if quick:
        steprate = prior.get("device_step_batches_per_s")
        if steprate is not None:
            record["device_step_batches_per_s"] = steprate
            record["device_step_source"] = "carried from prior full probe on %s" % (
                prior.get("device", "unknown device"),
            )
    else:
        ips, single_ips, _, _ = bench.run(batch_size=bs, steps=16,
                                          measure_pipeline=False)
        steprate = max(ips, single_ips) / bs
        record["device_step_batches_per_s"] = round(steprate, 3)

    # stage 4: full pipeline (uint8 wire, async staging)
    if not quick:
        try:
            rng = np.random.RandomState(0)
            main_, startup, loss = bench.build(bs)
            import paddle_tpu.fluid as fluid
            from paddle_tpu.executor import Scope, scope_guard
            from paddle_tpu.transpiler.bf16_transpiler import Bf16Transpiler

            exe = fluid.Executor(fluid.TPUPlace())
            with scope_guard(Scope(seed=0)):
                exe.run(startup)
                Bf16Transpiler().transpile(main_)
                pipe_ips = bench._run_pyreader_pass(
                    exe, main_, loss, bs, 12, 2, 2, rng, wire="uint8"
                )
            record["pyreader_uint8_batches_per_s"] = round(pipe_ips / bs, 3)
        except Exception as e:  # evidence table must still land
            record["pyreader_uint8_error"] = repr(e)
    elif "pyreader_uint8_batches_per_s" in prior:
        record["pyreader_uint8_batches_per_s"] = prior[
            "pyreader_uint8_batches_per_s"]

    # stage 5: device-resident epoch cache (PyReader cache_epoch=True) —
    # epoch 1 pays the wire once; epoch 2+ replays staged device arrays, so
    # the serve rate is queue handoff, not host assembly or transfer
    try:
        from paddle_tpu.py_reader import PyReader

        n_batches = 6

        def src():
            for _ in range(n_batches):
                yield {"image": batch}

        r = PyReader(["image"], capacity=4, cache_epoch=True)
        r.decorate_tensor_provider(src)
        r.start()
        for _ in r():  # epoch 1: stages + caches (wire path, timed above)
            pass
        t0 = time.perf_counter()
        served = 0
        for _ in range(3):  # epochs 2-4: cached replay
            r.start()
            for b in r():
                jax.block_until_ready(b["image"])
                served += 1
        dt = (time.perf_counter() - t0) / served
        record["cached_epoch_batches_per_s"] = round(1 / dt, 3)
    except Exception as e:
        record["cached_epoch_error"] = repr(e)

    # the verdict line: which stage binds?
    wire_bps = record["wire_uint8_batches_per_s"]
    step_bps = record.get("device_step_batches_per_s")
    if step_bps is not None:
        rates = {
            "host_assembly": record["host_batch_assembly_batches_per_s"],
            "wire_uint8": wire_bps,
            "device_step": step_bps,
        }
        record["binding_stage"] = min(rates, key=rates.get)
        record["wire_bound"] = bool(wire_bps < step_bps)
        record["keep_up_frac_ceiling_uint8"] = round(
            min(1.0, wire_bps / step_bps), 3
        )
        # with the epoch cached on device the wire stage drops out of the
        # loop: the keep-up ceiling becomes replay rate vs device step rate.
        # The wire-bound numbers above stay as the FIRST-epoch path; from
        # epoch 2 on, cache_epoch serving governs.
        if "cached_epoch_batches_per_s" in record:
            record["keep_up_frac_cached_epoch"] = round(
                min(1.0, record["cached_epoch_batches_per_s"] / step_bps), 3
            )
            record["cached_epoch_removes_wire_bound"] = bool(
                record["keep_up_frac_cached_epoch"] >= 0.9
            )

    with open(out_path, "w") as f:
        json.dump(record, f, indent=1)
    print(json.dumps(record, indent=1))


if __name__ == "__main__":
    main()
