"""Communication-volume audit for the multi-device lowering paths.

Compiles one real training step per parallelism path (dp all-reduce,
zero1 = ReduceStrategy.Reduce, fsdp and tp via declarative sharding rules,
dp x tp x sp x ep attention, dp x pp GPipe) over the 8-device mesh, parses
every collective out of the post-optimization HLO (the same HloIndex
machinery as tools/mfu_audit.py), and tabulates per collective: op kind,
tensor bytes, mesh axis (recovered from replica_groups), count per step, and
per-chip ring wire bytes.

Cross-check (--check, run by CI): the dp path's reduce-combined bytes must
match the analytic gradient bytes, and the zero1 path must additionally
all-gather exactly the shardable parameter bytes — both within 10%. The check
compares COMBINED TENSOR bytes, not instruction opcodes, because backends
spell the same semantics differently (the CPU partitioner emits the zero1
reduce-scatter as all-reduce + dynamic-slice; TPU emits a real
reduce-scatter) — the reduced bytes are invariant under that choice.

The sharding-rule paths (BuildStrategy.sharding_rules) check the wire
signatures of the two strategies the rule engine adds: the fsdp step must
all-gather each sharded parameter once per step and must not combine any
gradient as a full-tensor ring (check_fsdp); the tp step's dp gradient rings
must carry each grad at its stored shard size and its only tp collective is
the row-parallel activation all-reduce (check_tp) — all within 10%.

Ring wire formulas (per chip, group size p, full tensor B bytes):
    all-reduce      2(p-1)/p * B
    reduce-scatter   (p-1)/p * B
    all-gather       (p-1)/p * B
    all-to-all       (p-1)/p * B
    collective-permute   B (one neighbor send)

Also writes an analytic v5p-32 scaling projection (16 chips; v5e-measured
step anchors from MFU_AUDIT_*.json scaled by public v5p spec ratios — every
assumption recorded in the JSON).

Usage:
    python tools/comm_audit.py            # full audit -> COMM_AUDIT.json
    python tools/comm_audit.py --check    # CI smoke: dp+zero1 cross-check only
"""

import argparse
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from tools.mfu_audit import HloIndex, _parse_shapes  # noqa: E402

# --- collective opcodes (async "-start" halves count once; "-done" is free) --
_COLLECTIVES = (
    "all-reduce",
    "reduce-scatter",
    "all-gather",
    "all-to-all",
    "collective-permute",
)

_GROUPS_EXPLICIT_RE = re.compile(r"replica_groups=\{(\{[\d,]+\}(?:,\{[\d,]+\})*)\}")
_GROUPS_IOTA_RE = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?"
)


def _parse_replica_groups(d):
    """HLO replica_groups attr -> list of tuples of device ids (both the
    explicit {{0,1},{2,3}} and the iota [G,S]<=[dims]T(perm) spellings)."""
    m = _GROUPS_EXPLICIT_RE.search(d)
    if m:
        return [
            tuple(int(x) for x in g.split(","))
            for g in m.group(1)[1:-1].split("},{")
        ]
    m = _GROUPS_IOTA_RE.search(d)
    if m:
        n_groups, size = int(m.group(1)), int(m.group(2))
        dims = [int(x) for x in m.group(3).split(",")]
        ids = np.arange(int(np.prod(dims))).reshape(dims)
        if m.group(4):
            ids = ids.transpose([int(x) for x in m.group(4).split(",")])
        return [tuple(g) for g in ids.reshape(n_groups, size).tolist()]
    return []


def _axis_groups(mesh):
    """axis name -> frozenset of device-id groups that vary only that axis
    (logical ids 0..n-1 in mesh.devices order — what replica_groups use)."""
    sizes = [mesh.shape[a] for a in mesh.axis_names]
    ids = np.arange(int(np.prod(sizes))).reshape(sizes)
    out = {}
    for k, name in enumerate(mesh.axis_names):
        moved = np.moveaxis(ids, k, -1).reshape(-1, sizes[k])
        out[name] = frozenset(frozenset(g) for g in moved.tolist())
    return out


def _wire_bytes(kind, full_bytes, p):
    """Per-chip ring wire bytes for one instance."""
    if p <= 1:
        return 0
    if kind == "all-reduce":
        return 2 * (p - 1) * full_bytes // p
    if kind == "collective-permute":
        return full_bytes
    return (p - 1) * full_bytes // p  # reduce-scatter / all-gather / all-to-all


def audit_hlo(hlo_text, mesh):
    """Parse one compiled module's collectives into table rows + totals."""
    idx = HloIndex(hlo_text)
    axis_groups = _axis_groups(mesh)
    rows = {}
    for name in idx.defs:
        op = idx.opcode(name)
        base = op[:-6] if op.endswith("-start") else op
        if base not in _COLLECTIVES or op.endswith("-done"):
            continue
        d = idx.line(name)
        res_bytes = sum(b for _, _, b in idx.result_shapes(name))
        if base == "collective-permute":
            # source_target_pairs, not replica_groups; the ring length is the
            # cycle of the permutation (a 2-ring inside a dp2xsp2 mesh lists
            # 8 pairs but each device's cycle closes after 2 hops)
            m = re.search(r"source_target_pairs=\{\{(.*?)\}\}", d)
            pairs = (
                [tuple(int(x) for x in pr.split(","))
                 for pr in m.group(1).split("},{")]
                if m
                else []
            )
            nxt = dict(pairs)
            cycle, cur = [0], nxt.get(0)
            while cur not in (None, 0) and len(cycle) <= len(pairs):
                cycle.append(cur)
                cur = nxt.get(cur)
            p = len(cycle)
            groups = [tuple(cycle)] if len(cycle) > 1 else None
            full = res_bytes
        else:
            groups = _parse_replica_groups(d)
            p = len(groups[0]) if groups else mesh.size
            # result of reduce-scatter is the 1/p shard; of the others, the
            # full combined tensor
            full = res_bytes * p if base == "reduce-scatter" else res_bytes
        axis = "?"
        if groups:
            gset = frozenset(frozenset(g) for g in groups)
            for a, expect in axis_groups.items():
                if gset <= expect:
                    axis = a
                    break
            else:
                axis = "mixed(%d)" % p
        key = (base, axis, p, full)
        if key in rows:
            rows[key]["count"] += 1
        else:
            rows[key] = {
                "op": base,
                "axis": axis,
                "group_size": p,
                "tensor_bytes": full,
                "wire_bytes_per_chip": _wire_bytes(base, full, p),
                "count": 1,
            }
    table = sorted(
        rows.values(),
        key=lambda r: -r["wire_bytes_per_chip"] * r["count"],
    )
    totals = {
        "reduced_bytes": sum(
            r["tensor_bytes"] * r["count"]
            for r in table
            if r["op"] in ("all-reduce", "reduce-scatter")
        ),
        "gathered_bytes": sum(
            r["tensor_bytes"] * r["count"] for r in table if r["op"] == "all-gather"
        ),
        "wire_bytes_per_chip": sum(
            r["wire_bytes_per_chip"] * r["count"] for r in table
        ),
        "collective_count": sum(r["count"] for r in table),
    }
    return {"collectives": table, "totals": totals}


# ---------------------------------------------------------------------------
# model steps per parallelism path
# ---------------------------------------------------------------------------


def _build_mlp(d_in=64, d_hidden=128, classes=8):
    """BN-free MLP whose every parameter (incl. the size-8 bias) has a
    leading dim divisible by 8 — the whole gradient is shardable, so the
    zero1 analytic check has no replicated remainder to excuse."""
    import paddle_tpu.fluid as fluid

    x = fluid.layers.data(name="x", shape=[d_in], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="int64")
    h = fluid.layers.fc(x, size=d_hidden, act="relu")
    logits = fluid.layers.fc(h, size=classes)
    loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(logits, y))
    return loss


def _grad_bytes(program):
    """Analytic f32 gradient bytes: one grad per trainable parameter."""
    total = 0
    for p in program.global_block().all_parameters():
        if getattr(p, "trainable", True):
            total += int(np.prod(p.shape)) * 4
    return total


def _shardable_param_bytes(program, mesh, axis="dp", rules=None):
    """Analytic f32 bytes of the trainable parameters that end up sharded.

    Attribute mode (rules=None): the ZeRO-1 criterion — dim 0 divisible by
    `axis`'s extent (collectives.zero1_shardable).

    Rules mode: parameters whose declarative sharding rule survives pruning
    on this mesh (parallel.sharding_rules.Resolver — same resolver the
    executor uses, so divisibility degradation matches the compiled step).
    """
    total = 0
    if rules is not None:
        from paddle_tpu.parallel.sharding_rules import Resolver

        res = Resolver(mesh, rules=rules)
        for p in program.global_block().all_parameters():
            if getattr(p, "trainable", True) and res.rule_spec(
                p.name, tuple(p.shape)
            ) is not None:
                total += int(np.prod(p.shape)) * 4
        return total
    from paddle_tpu.parallel.collectives import zero1_shardable

    for p in program.global_block().all_parameters():
        if getattr(p, "trainable", True) and zero1_shardable(p.shape, mesh, axis):
            total += int(np.prod(p.shape)) * 4
    return total


def _mlp_step_hlo(reduce_strategy):
    """Compile+run one MLP Adam step under the given ReduceStrategy; return
    (hlo_text, mesh, main_program)."""
    import jax

    import paddle_tpu.fluid as fluid
    from paddle_tpu import framework
    from paddle_tpu.executor import Scope, scope_guard
    from paddle_tpu.parallel_executor import BuildStrategy

    main, startup = framework.Program(), framework.Program()
    with fluid.unique_name.guard(), fluid.program_guard(main, startup):
        loss = _build_mlp()
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    strat = BuildStrategy()
    strat.reduce_strategy = reduce_strategy
    n = jax.device_count()
    rng = np.random.RandomState(0)
    x = rng.randn(4 * n, 64).astype("float32")
    y = rng.randint(0, 8, (4 * n, 1)).astype("int64")
    scope = Scope(seed=0)
    with scope_guard(scope):
        fluid.Executor().run(startup)
        pe = fluid.ParallelExecutor(
            loss_name=loss.name, main_program=main, build_strategy=strat,
            scope=scope,
        )
        pe.run(fetch_list=[loss.name], feed={"x": x, "y": y})
        hlo = pe.compiled_hlo()
        mesh = pe._mesh
    return hlo, mesh, main


def _rules_mlp_step_hlo(mesh_kwargs, rules):
    """Compile+run one MLP Adam step with declarative sharding rules
    (BuildStrategy.sharding_rules, the PR-13 engine) on the given mesh;
    return (hlo_text, mesh, main_program)."""
    import jax

    import paddle_tpu.fluid as fluid
    from paddle_tpu import framework
    from paddle_tpu.executor import Scope, scope_guard
    from paddle_tpu.parallel import MeshConfig
    from paddle_tpu.parallel_executor import BuildStrategy

    main, startup = framework.Program(), framework.Program()
    with fluid.unique_name.guard(), fluid.program_guard(main, startup):
        loss = _build_mlp()
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    strat = BuildStrategy()
    strat.sharding_rules = rules
    n = jax.device_count()
    rng = np.random.RandomState(0)
    x = rng.randn(4 * n, 64).astype("float32")
    y = rng.randint(0, 8, (4 * n, 1)).astype("int64")
    scope = Scope(seed=0)
    with scope_guard(scope):
        fluid.Executor().run(startup)
        pe = fluid.ParallelExecutor(
            loss_name=loss.name, main_program=main, build_strategy=strat,
            scope=scope, mesh_config=MeshConfig(**mesh_kwargs),
        )
        pe.run(fetch_list=[loss.name], feed={"x": x, "y": y})
        hlo = pe.compiled_hlo()
        mesh = pe._mesh
    return hlo, mesh, main


# fc params in _build_mlp (unique_name.guard): fc_0.w_0 (64,128),
# fc_0.b_0 (128,), fc_1.w_0 (128,8), fc_1.b_0 (8,)
_FSDP_RULES = [(r"^fc_\d+\.(w|b)_0$", ("fsdp",))]
_TP_RULES = [
    (r"^fc_0\.w_0$", (None, "tp")),   # column-parallel: hidden over tp
    (r"^fc_0\.b_0$", ("tp",)),        # bias follows its weight's out dim
    (r"^fc_1\.w_0$", ("tp", None)),   # row-parallel: reduce lands after fc_1
]


def _fsdp_step_hlo():
    """dp2 x fsdp4 MLP step: every parameter (and its Adam moments, via the
    resolver's accumulator alias) stored 1/4-sharded over fsdp."""
    return _rules_mlp_step_hlo(dict(dp=2, fsdp=4), _FSDP_RULES)


def _tp_step_hlo():
    """dp4 x tp2 MLP step: Megatron column/row pair on the two fc layers."""
    return _rules_mlp_step_hlo(dict(dp=4, tp=2), _TP_RULES)


def _attention_step_hlo():
    """dp x tp x sp x ep attention-LM step (the dryrun_multichip stage-2 model)."""
    import jax

    import paddle_tpu.fluid as fluid
    from paddle_tpu import framework
    from paddle_tpu.executor import Scope, scope_guard
    from paddle_tpu.parallel import MeshConfig, shard_parameter

    n = jax.device_count()
    if n % 8:
        return None, None
    cfg = MeshConfig(dp=n // 8, tp=2, sp=2, ep=2)
    VOCAB, D, HEADS, T = 64, 16, 2, 8
    main, startup = framework.Program(), framework.Program()
    with fluid.unique_name.guard(), fluid.program_guard(main, startup):
        tok = fluid.layers.data(
            name="tok", shape=[-1, T, 1], dtype="int64", append_batch_size=False
        )
        lbl = fluid.layers.data(
            name="lbl", shape=[-1, 1], dtype="int64", append_batch_size=False
        )
        emb = fluid.layers.distributed_embedding(tok, size=[VOCAB, D])
        qkv = fluid.layers.fc(emb, size=3 * D, num_flatten_dims=2, bias_attr=False)
        for p in main.global_block().all_parameters():
            if p.shape == (D, 3 * D):
                shard_parameter(p, (None, "tp"))
        q, k, v = fluid.layers.split(qkv, 3, dim=2)

        def heads(x):
            r = fluid.layers.reshape(x, [0, 0, HEADS, D // HEADS])
            return fluid.layers.transpose(r, [0, 2, 1, 3])

        att = fluid.layers.ring_attention(heads(q), heads(k), heads(v), causal=True)
        att = fluid.layers.reshape(
            fluid.layers.transpose(att, [0, 2, 1, 3]), [0, 0, D]
        )
        pooled = fluid.layers.reduce_mean(att, dim=[1])
        logits = fluid.layers.fc(pooled, size=4)
        loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(logits, lbl))
        fluid.optimizer.Adam(1e-3).minimize(loss)
    dp = cfg.resolve(n)["dp"]
    rng = np.random.RandomState(1)
    toks = rng.randint(0, VOCAB, (2 * dp, T, 1)).astype("int64")
    lbls = rng.randint(0, 4, (2 * dp, 1)).astype("int64")
    scope = Scope(seed=1)
    with scope_guard(scope):
        fluid.Executor().run(startup)
        pe = fluid.ParallelExecutor(
            loss_name=loss.name, main_program=main, scope=scope, mesh_config=cfg,
        )
        pe.run(fetch_list=[loss.name], feed={"tok": toks, "lbl": lbls})
        hlo = pe.compiled_hlo()
        mesh = pe._mesh
    return hlo, mesh


def _gpipe_step_hlo():
    """dp x pp GPipe train step on the PROGRAM path (dryrun_multichip
    stage 3): a heterogeneous-width fluid MLP lowered end-to-end by
    ParallelExecutor (MeshConfig(pp=4) -> partition + schedule), audited
    from its own compiled_hlo() — the collectives counted here are the
    ones real Program training pays, not a hand-built stand-in."""
    import jax

    import paddle_tpu.fluid as fluid
    from paddle_tpu import framework
    from paddle_tpu.executor import Scope, scope_guard
    from paddle_tpu.parallel import MeshConfig
    from paddle_tpu.parallel_executor import ExecutionStrategy

    n = jax.device_count()
    if n % 4:
        return None, None
    pp = 4
    main, startup = framework.Program(), framework.Program()
    with fluid.unique_name.guard(), fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[16], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="int64")
        h = x
        for w in (48, 32, 24):
            h = fluid.layers.fc(h, size=w, act="relu")
        logits = fluid.layers.fc(h, size=4)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, y)
        )
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
    es = ExecutionStrategy()
    es.pipeline_schedule = "gpipe"
    es.num_microbatches = 4
    dp = n // pp
    rng = np.random.RandomState(5)
    xs = rng.randn(8 * dp, 16).astype("float32")
    ys = rng.randint(0, 4, (8 * dp, 1)).astype("int64")
    scope = Scope(seed=5)
    with scope_guard(scope):
        fluid.Executor().run(startup)
        pe = fluid.ParallelExecutor(
            loss_name=loss.name, main_program=main, scope=scope,
            mesh_config=MeshConfig(dp=dp, pp=pp), exec_strategy=es,
        )
        pe.run(fetch_list=[loss.name], feed={"x": xs, "y": ys})
        hlo = pe.compiled_hlo()
        mesh = pe._mesh
    return hlo, mesh


# ---------------------------------------------------------------------------
# analytic cross-checks (backend-robust: combined tensor bytes, not opcodes)
# ---------------------------------------------------------------------------


def _rule_resolver(mesh, rules):
    from paddle_tpu.parallel.sharding_rules import Resolver, ShardingRules

    if rules is not None and not isinstance(rules, ShardingRules):
        rules = ShardingRules(rules)
    return Resolver(mesh, rules=rules)


def _rule_sharded_param_sizes(program, mesh, rules):
    """f32 byte size of each trainable parameter whose sharding rule
    survives pruning on this mesh (the tensors FSDP/TP actually shard)."""
    res = _rule_resolver(mesh, rules)
    return [
        int(np.prod(p.shape)) * 4
        for p in program.global_block().all_parameters()
        if getattr(p, "trainable", True)
        and res.rule_spec(p.name, tuple(p.shape)) is not None
    ]


def _dp_grad_ring_bytes(program, mesh, rules):
    """Analytic bytes the dp gradient all-reduces carry: each grad rides the
    ring at its parameter's STORED shard size — a rule-sharded grad is
    constrained to the param's layout before the optimizer
    (sharding_rules.opt_constrain_ins), so its dp ring moves 1/shards of
    the tensor."""
    res = _rule_resolver(mesh, rules)
    total = 0
    for p in program.global_block().all_parameters():
        if not getattr(p, "trainable", True):
            continue
        spec = res.rule_spec(p.name, tuple(p.shape))
        factor = 1
        for entry in spec or ():
            axes = entry if isinstance(entry, tuple) else (
                (entry,) if entry else ()
            )
            for a in axes:
                factor *= mesh.shape.get(a, 1)
        total += int(np.prod(p.shape)) * 4 // factor
    return total


def check_dp(audit, grad_bytes, tol=0.10):
    """The dp step must reduce-combine exactly the gradients (+ the scalar
    loss fetch, <<1%)."""
    reduced = audit["totals"]["reduced_bytes"]
    err = abs(reduced - grad_bytes) / grad_bytes
    assert err <= tol, (
        "dp reduced bytes %d vs analytic grad bytes %d (%.1f%% off)"
        % (reduced, grad_bytes, 100 * err)
    )
    return err


def check_zero1(audit, grad_bytes, shardable_param_bytes, tol=0.10):
    """The zero1 step reduce-combines the same gradient bytes AND gathers
    back exactly the shardable parameter bytes (each updated shard returns
    to every rank once)."""
    reduced = audit["totals"]["reduced_bytes"]
    gathered = audit["totals"]["gathered_bytes"]
    r_err = abs(reduced - grad_bytes) / grad_bytes
    g_err = abs(gathered - shardable_param_bytes) / shardable_param_bytes
    assert r_err <= tol, (
        "zero1 reduced bytes %d vs analytic grad bytes %d (%.1f%% off)"
        % (reduced, grad_bytes, 100 * r_err)
    )
    assert g_err <= tol, (
        "zero1 gathered bytes %d vs shardable param bytes %d (%.1f%% off)"
        % (gathered, shardable_param_bytes, 100 * g_err)
    )
    return r_err, g_err


def check_tp(audit, dp_ring_bytes, act_ar_bytes, tol=0.10):
    """The tp (Megatron column/row pair) step must all-reduce (a) every
    gradient over dp at its stored shard size, and (b) exactly one
    activation over tp: the row-parallel matmul's partial-sum output,
    (batch/dp) x classes f32. Backward adds NO tp collective here because
    the first operand (the data feed) takes no gradient — the dx
    all-reduce Megatron pays per layer only appears between stacked pairs."""
    dp_reduced = sum(
        r["tensor_bytes"] * r["count"]
        for r in audit["collectives"]
        if r["op"] in ("all-reduce", "reduce-scatter") and r["axis"] == "dp"
    )
    tp_reduced = sum(
        r["tensor_bytes"] * r["count"]
        for r in audit["collectives"]
        if r["op"] in ("all-reduce", "reduce-scatter") and r["axis"] == "tp"
    )
    dp_err = abs(dp_reduced - dp_ring_bytes) / dp_ring_bytes
    tp_err = abs(tp_reduced - act_ar_bytes) / act_ar_bytes
    assert dp_err <= tol, (
        "tp path dp-reduced bytes %d vs analytic grad-shard bytes %d "
        "(%.1f%% off)" % (dp_reduced, dp_ring_bytes, 100 * dp_err)
    )
    assert tp_err <= tol, (
        "tp path tp-reduced bytes %d vs analytic row-parallel activation "
        "bytes %d (%.1f%% off)" % (tp_reduced, act_ar_bytes, 100 * tp_err)
    )
    return dp_err, tp_err


def check_fsdp(audit, sharded_param_sizes, grad_bytes, tol=0.10):
    """The fsdp step must all-gather each rule-sharded parameter over the
    fsdp axis exactly once per step (weight streaming; a second gather of
    the same tensor is the double-gather regression the ZeRO-1 path also
    guards against), and must NEVER combine gradients as full-tensor rings
    — FSDP's grad combine happens at shard granularity, so reduced bytes
    stay far below the replicated-grad total. The gather check matches
    all-gathers BY TENSOR SIZE against the sharded parameter list; the
    partitioner's discretionary activation gathers (it may rematerialize a
    batch-sharded activation instead of reducing a grad — observed on the
    CPU partitioner) don't collide with parameter sizes in this model."""
    sizes = set(sharded_param_sizes)
    param_bytes = sum(sharded_param_sizes)
    gathered = sum(
        r["tensor_bytes"] * r["count"]
        for r in audit["collectives"]
        if r["op"] == "all-gather"
        and r["axis"] == "fsdp"
        and r["tensor_bytes"] in sizes
    )
    g_err = abs(gathered - param_bytes) / param_bytes
    assert g_err <= tol, (
        "fsdp param-gather bytes %d vs sharded param bytes %d (%.1f%% off)"
        % (gathered, param_bytes, 100 * g_err)
    )
    reduced = audit["totals"]["reduced_bytes"]
    assert reduced < grad_bytes / 2, (
        "fsdp path reduced %d bytes — full-tensor gradient rings appeared "
        "(grads should combine at 1/fsdp shard granularity, << %d)"
        % (reduced, grad_bytes)
    )
    return g_err


def analytic_wire(grad_bytes, shardable_param_bytes, p):
    """Ideal ring wire per chip for both strategies. zero1's total equals the
    all-reduce total when every gradient is shardable: RS(G) + AG(P) =
    (p-1)/p*(G+P) = 2(p-1)/p*G for G == P — the ZeRO-1 claim that sharding
    optimizer state costs no extra wire."""
    ar = 2 * (p - 1) * grad_bytes // p
    rest = grad_bytes - shardable_param_bytes  # non-shardable grads stay AR
    z1 = (
        (p - 1) * shardable_param_bytes // p  # reduce-scatter(grad shard)
        + (p - 1) * shardable_param_bytes // p  # all-gather(param)
        + 2 * (p - 1) * rest // p
    )
    return {"allreduce_wire_per_chip": ar, "zero1_wire_per_chip": z1}


# ---------------------------------------------------------------------------
# v5p-32 projection (analytic; all inputs recorded)
# ---------------------------------------------------------------------------

# anchors measured on the v5e bench chip (MFU_AUDIT_*.json in repo root)
_V5E_ANCHORS = {
    "resnet50_bs256": {
        "wall_ms": 117.8,
        "hlo_tflops": 6.01,
        "hlo_gb": 127.5,
        "images_per_step": 256,
        "optimizer": "momentum_f32",
        "source": "MFU_AUDIT_resnet.json",
    },
    "transformer_8x1024_d2048_L4": {
        "wall_ms": 218.4,
        "hlo_tflops": 26.31,
        "hlo_gb": 182.59,
        "optimizer": "adam_bf16_moments",
        "source": "MFU_AUDIT_transformer.json",
    },
}

_ASSUMPTIONS = {
    "v5e_peak_mm_tflops": 192.0,  # measured probe (tools/mfu_audit.py)
    "v5e_peak_bw_gbs": 676.0,  # measured probe
    "v5p_peak_bf16_tflops": 459.0,  # public spec sheet
    "v5p_hbm_gbs": 2765.0,  # public spec sheet
    "v5p_hbm_gb_per_chip": 95,
    "v5p_ici_gbs_per_chip": 600.0,  # 4800 Gbit/s aggregate per chip
    "v5p_ici_efficiency": 0.66,  # achievable fraction of nominal ICI
    "v5p32_chips": 16,  # a v5p-32 slice = 32 TensorCores = 16 chips
    "method": (
        "per-chip step time bracketed by scaling the measured v5e wall "
        "by the compute-peak ratio (if MXU-bound) and the HBM-bandwidth "
        "ratio (if HBM-bound); 16-way dp adds the gradient ring time, "
        "reported overlapped (max) and serial (sum)"
    ),
}


def _project_model(anchor, param_bytes, opt_state_bytes_replicated):
    a = _ASSUMPTIONS
    chips = a["v5p32_chips"]
    f_compute = a["v5p_peak_bf16_tflops"] / a["v5e_peak_mm_tflops"]
    f_hbm = a["v5p_hbm_gbs"] / a["v5e_peak_bw_gbs"]
    # per-chip step-time bracket: the step speeds up by at least the smaller
    # ratio and at most the larger, whichever resource bounds it
    t_fast_ms = anchor["wall_ms"] / max(f_compute, f_hbm)
    t_slow_ms = anchor["wall_ms"] / min(f_compute, f_hbm)
    grad_bytes = param_bytes  # f32 grads, one per param element
    wire = 2 * (chips - 1) * grad_bytes // chips  # AR == zero1 RS+AG wire
    ici_gbs = a["v5p_ici_gbs_per_chip"] * a["v5p_ici_efficiency"]
    t_ici_ms = wire / ici_gbs / 1e6
    out = {
        "anchor": anchor,
        "param_bytes": param_bytes,
        "grad_allreduce_wire_per_chip_bytes": wire,
        "ici_ms_per_step": round(t_ici_ms, 3),
        "per_chip_step_ms_range": [round(t_fast_ms, 1), round(t_slow_ms, 1)],
        "step_ms_overlapped_range": [
            round(max(t_fast_ms, t_ici_ms), 1),
            round(max(t_slow_ms, t_ici_ms), 1),
        ],
        "step_ms_serial_range": [
            round(t_fast_ms + t_ici_ms, 1),
            round(t_slow_ms + t_ici_ms, 1),
        ],
        "optimizer_state_bytes_per_chip_replicated": opt_state_bytes_replicated,
        "optimizer_state_bytes_per_chip_zero1": opt_state_bytes_replicated
        // chips,
    }
    if "images_per_step" in anchor:
        per_chip = anchor["images_per_step"]
        out["v5p32_images_per_sec_range"] = [
            round(chips * per_chip / (t_slow_ms + t_ici_ms) * 1e3),
            round(chips * per_chip / max(t_fast_ms, t_ici_ms) * 1e3),
        ]
    else:
        tf = anchor["hlo_tflops"]
        out["v5p32_tflops_per_sec_range"] = [
            round(chips * tf / (t_slow_ms + t_ici_ms) * 1e3, 1),
            round(chips * tf / max(t_fast_ms, t_ici_ms) * 1e3, 1),
        ]
    return out


def build_projection():
    """Param/state bytes come from the ACTUAL bench programs (IR only — no
    step is run), so the projection tracks the models as they evolve."""
    import bench

    main_r, _startup, _loss = bench.build(256)
    p_r = _grad_bytes(main_r)
    main_t, _startup_t, _feed, _loss_t, _flops = bench.build_transformer()
    p_t = _grad_bytes(main_t)
    return {
        "assumptions": _ASSUMPTIONS,
        "resnet50": _project_model(
            _V5E_ANCHORS["resnet50_bs256"], p_r,
            # Momentum: one f32 velocity per param element
            p_r,
        ),
        "transformer": _project_model(
            _V5E_ANCHORS["transformer_8x1024_d2048_L4"], p_t,
            # Adam with bf16 moments: two moments at 2 bytes per element ==
            # one f32-equivalent copy of the params
            p_t,
        ),
    }


# ---------------------------------------------------------------------------


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--check", action="store_true",
                    help="CI smoke: dp+zero1 audits + analytic cross-check "
                         "only, no file writes")
    ap.add_argument("--out", default="COMM_AUDIT.json")
    args = ap.parse_args()

    from paddle_tpu.platform_setup import force_virtual_cpu_devices

    force_virtual_cpu_devices(8)
    import jax

    from paddle_tpu.parallel_executor import ReduceStrategy

    n = jax.device_count()
    hlo_dp, mesh_dp, prog = _mlp_step_hlo(ReduceStrategy.AllReduce)
    hlo_z1, mesh_z1, _ = _mlp_step_hlo(ReduceStrategy.Reduce)
    dp_audit = audit_hlo(hlo_dp, mesh_dp)
    z1_audit = audit_hlo(hlo_z1, mesh_z1)

    grad_bytes = _grad_bytes(prog)
    shardable = _shardable_param_bytes(prog, mesh_dp)
    dp_err = check_dp(dp_audit, grad_bytes)
    z1_r_err, z1_g_err = check_zero1(z1_audit, grad_bytes, shardable)

    # -- declarative sharding rules (PR 13): fsdp and tp paths --------------
    hlo_f, mesh_f, prog_f = _fsdp_step_hlo()
    hlo_t, mesh_t, prog_t = _tp_step_hlo()
    f_audit = audit_hlo(hlo_f, mesh_f)
    t_audit = audit_hlo(hlo_t, mesh_t)
    f_sizes = _rule_sharded_param_sizes(prog_f, mesh_f, _FSDP_RULES)
    f_err = check_fsdp(f_audit, f_sizes, _grad_bytes(prog_f))
    t_dp_bytes = _dp_grad_ring_bytes(prog_t, mesh_t, _TP_RULES)
    # row-parallel forward all-reduce: the logits partial-sum, per dp shard
    t_act_bytes = 4 * n // mesh_t.shape["dp"] * 8 * 4  # batch/dp x classes f32
    t_dp_err, t_tp_err = check_tp(t_audit, t_dp_bytes, t_act_bytes)

    print(
        "check ok on %d devices: dp reduced within %.2f%%, zero1 reduced "
        "within %.2f%% / gathered within %.2f%%, fsdp param-gather within "
        "%.2f%%, tp dp-ring within %.2f%% / tp-act within %.2f%% of analytic"
        % (n, 100 * dp_err, 100 * z1_r_err, 100 * z1_g_err, 100 * f_err,
           100 * t_dp_err, 100 * t_tp_err)
    )
    if args.check:
        return

    out = {
        "devices": n,
        "model": "MLP 64->128->8, Adam (dp/zero1 paths)",
        "analytic": dict(
            grad_bytes=grad_bytes,
            shardable_param_bytes=shardable,
            **analytic_wire(grad_bytes, shardable, mesh_dp.shape["dp"]),
        ),
        "paths": {
            "dp_allreduce": dp_audit,
            "zero1": z1_audit,
            "fsdp": f_audit,
            "tp": t_audit,
        },
        "sharding_rules": {
            "fsdp": {
                "mesh": "dp2 x fsdp4",
                "rules": [[p, list(s)] for p, s in _FSDP_RULES],
                "sharded_param_bytes": sum(f_sizes),
                "analytic_param_gather_wire_per_chip": sum(
                    3 * b // 4 for b in f_sizes
                ),
            },
            "tp": {
                "mesh": "dp4 x tp2",
                "rules": [[p, list(s)] for p, s in _TP_RULES],
                "dp_grad_ring_bytes": t_dp_bytes,
                "rowparallel_act_allreduce_bytes": t_act_bytes,
            },
        },
        "check_errors_pct": {
            "dp_reduced": round(100 * dp_err, 2),
            "zero1_reduced": round(100 * z1_r_err, 2),
            "zero1_gathered": round(100 * z1_g_err, 2),
            "fsdp_param_gather": round(100 * f_err, 2),
            "tp_dp_ring": round(100 * t_dp_err, 2),
            "tp_act_allreduce": round(100 * t_tp_err, 2),
        },
    }

    hlo_att, mesh_att = _attention_step_hlo()
    if hlo_att:
        out["paths"]["tp_sp_ep"] = audit_hlo(hlo_att, mesh_att)
    hlo_pp, mesh_pp = _gpipe_step_hlo()
    if hlo_pp:
        out["paths"]["dp_pp_gpipe"] = audit_hlo(hlo_pp, mesh_pp)

    out["v5p32_projection"] = build_projection()

    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print("wrote", args.out)
    fmt = "%-12s %-18s %-8s %5s %12s %12s %5s"
    for path, audit in out["paths"].items():
        print("\n[%s] wire/chip/step = %d B" % (
            path, audit["totals"]["wire_bytes_per_chip"]))
        print(fmt % ("path", "op", "axis", "p", "tensor_B", "wire_B/chip",
                     "count"))
        for r in audit["collectives"]:
            print(fmt % (path, r["op"], r["axis"], r["group_size"],
                         r["tensor_bytes"], r["wire_bytes_per_chip"],
                         r["count"]))


if __name__ == "__main__":
    main()
