"""Generate Kubernetes manifests for distributed training jobs.

Reference analog: benchmark/fluid/kube_gen_job.py + kube_templates/ — the
reference emits pserver ReplicaSet + trainer Job yamls (pserver mode) or an
NCCL2 multi-node trainer set. The TPU-native redesign keeps the pserver mode
(our parameter-shard server, distributed/listen_and_serv.py) and replaces the
NCCL2 mode with `spmd`: one pod per TPU host in a StatefulSet, rendezvousing
through jax.distributed (parallel/multihost.py) over the stable headless-
service DNS of pod 0 — after which the GSPMD mesh spans all hosts and there
is nothing else to launch (no NCCL ids, no per-GPU processes).

Env contract (consumed by parallel.multihost.init_distributed and the
DistributeTranspiler config):
  PADDLE_TRAINER_ENDPOINTS  comma list, entry 0 = coordinator (spmd mode)
  PADDLE_TRAINER_ID         pod ordinal (derived from the StatefulSet name)
  PADDLE_PSERVER_ENDPOINTS  comma list of pserver addresses (pserver mode)
  PADDLE_CURRENT_ENDPOINT   this pserver's own address (pserver mode)

Usage: python tools/kube_gen_job.py --jobname myjob --mode spmd --hosts 4 \
           --tpu-accelerator v5p-32 --image my/image --entry "python train.py"
Writes <jobname>.yaml (use --out -) for `kubectl apply -f`.
"""

import argparse
import sys


def _env(name, value):
    return {"name": name, "value": str(value)}


def _container(args, env, resources=None):
    c = {
        "name": "trainer",
        "image": args.image,
        # the ordinal is only available through the pod name; export it
        # before the entry (reference kube_templates derive trainer id the
        # same way from the job name)
        "command": [
            "bash",
            "-c",
            'export PADDLE_TRAINER_ID="${HOSTNAME##*-}"; exec ' + args.entry,
        ],
        "env": env,
    }
    if resources:
        c["resources"] = resources
    return c


def spmd_manifests(args):
    """Headless service + StatefulSet: one pod per TPU host; pod 0's stable
    DNS name is the jax.distributed coordinator."""
    svc = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": args.jobname},
        "spec": {
            "clusterIP": "None",
            "selector": {"app": args.jobname},
            "ports": [{"port": args.port, "name": "coord"}],
        },
    }
    endpoints = ",".join(
        "%s-%d.%s:%d" % (args.jobname, i, args.jobname, args.port)
        for i in range(args.hosts)
    )
    env = [
        _env("PADDLE_TRAINER_ENDPOINTS", endpoints),
        _env("PADDLE_TRAINERS_NUM", args.hosts),
    ]
    resources = None
    pod_spec = {
        "containers": [_container(args, env, resources)],
    }
    if args.tpu_accelerator:
        # GKE TPU scheduling idiom: the accelerator/topology node selectors
        # place one pod per TPU host of the slice; the google.com/tpu
        # resource claims that host's chips
        pod_spec["nodeSelector"] = {
            "cloud.google.com/gke-tpu-accelerator": args.tpu_accelerator,
            "cloud.google.com/gke-tpu-topology": args.tpu_topology or "",
        }
        pod_spec["containers"][0]["resources"] = {
            "limits": {"google.com/tpu": args.tpu_chips_per_host}
        }
    sts = {
        "apiVersion": "apps/v1",
        "kind": "StatefulSet",
        "metadata": {"name": args.jobname},
        "spec": {
            "serviceName": args.jobname,
            "replicas": args.hosts,
            "podManagementPolicy": "Parallel",
            "selector": {"matchLabels": {"app": args.jobname}},
            "template": {
                "metadata": {"labels": {"app": args.jobname}},
                "spec": pod_spec,
            },
        },
    }
    return [svc, sts]


def pserver_manifests(args):
    """Pserver ReplicaSet + trainer Job (reference kube_templates/pserver +
    trainer), wired for our socket-RPC pserver."""
    ps_endpoints = ",".join(
        "%s-pserver-%d.%s-pserver:%d" % (args.jobname, i, args.jobname, args.port)
        for i in range(args.pservers)
    )
    svc = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": args.jobname + "-pserver"},
        "spec": {
            "clusterIP": "None",
            "selector": {"app": args.jobname + "-pserver"},
            "ports": [{"port": args.port, "name": "rpc"}],
        },
    }
    ps_env = [
        _env("PADDLE_PSERVER_ENDPOINTS", ps_endpoints),
        _env("PADDLE_TRAINERS_NUM", args.trainers),
        _env("TRAINING_ROLE", "PSERVER"),
    ]
    ps = {
        "apiVersion": "apps/v1",
        "kind": "StatefulSet",
        "metadata": {"name": args.jobname + "-pserver"},
        "spec": {
            "serviceName": args.jobname + "-pserver",
            "replicas": args.pservers,
            "podManagementPolicy": "Parallel",
            "selector": {"matchLabels": {"app": args.jobname + "-pserver"}},
            "template": {
                "metadata": {"labels": {"app": args.jobname + "-pserver"}},
                "spec": {
                    "containers": [
                        {
                            "name": "pserver",
                            "image": args.image,
                            "command": [
                                "bash",
                                "-c",
                                'export PADDLE_CURRENT_ENDPOINT='
                                '"${HOSTNAME}.%s-pserver:%d"; exec %s'
                                % (args.jobname, args.port, args.entry),
                            ],
                            "env": ps_env,
                        }
                    ]
                },
            },
        },
    }
    tr_env = [
        _env("PADDLE_PSERVER_ENDPOINTS", ps_endpoints),
        _env("PADDLE_TRAINERS_NUM", args.trainers),
        _env("TRAINING_ROLE", "TRAINER"),
    ]
    tr = {
        "apiVersion": "batch/v1",
        "kind": "Job",
        "metadata": {"name": args.jobname + "-trainer"},
        "spec": {
            "completions": args.trainers,
            "parallelism": args.trainers,
            "completionMode": "Indexed",
            "template": {
                "metadata": {"labels": {"app": args.jobname + "-trainer"}},
                "spec": {
                    "restartPolicy": "Never",
                    "containers": [
                        {
                            "name": "trainer",
                            "image": args.image,
                            "command": [
                                "bash",
                                "-c",
                                'export PADDLE_TRAINER_ID='
                                '"${JOB_COMPLETION_INDEX}"; exec ' + args.entry,
                            ],
                            "env": tr_env,
                        }
                    ],
                },
            },
        },
    }
    return [svc, ps, tr]


def local_manifests(args):
    return [
        {
            "apiVersion": "batch/v1",
            "kind": "Job",
            "metadata": {"name": args.jobname},
            "spec": {
                "template": {
                    "spec": {
                        "restartPolicy": "Never",
                        "containers": [_container(args, [])],
                    }
                }
            },
        }
    ]


def generate(args):
    return {
        "spmd": spmd_manifests,
        "pserver": pserver_manifests,
        "local": local_manifests,
    }[args.mode](args)


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="Generate dist-job k8s manifests")
    p.add_argument("--jobname", default="paddletpu")
    p.add_argument("--mode", default="spmd", choices=["spmd", "pserver", "local"])
    p.add_argument("--image", default="paddle-tpu:latest")
    p.add_argument("--entry", default="python train.py")
    p.add_argument("--port", type=int, default=8476)
    p.add_argument("--hosts", type=int, default=4, help="TPU hosts (spmd)")
    p.add_argument("--pservers", type=int, default=2)
    p.add_argument("--trainers", type=int, default=2)
    p.add_argument("--tpu-accelerator", default=None,
                   help="GKE accelerator type, e.g. tpu-v5p-slice")
    p.add_argument("--tpu-topology", default=None, help="e.g. 2x2x4")
    p.add_argument("--tpu-chips-per-host", type=int, default=4)
    p.add_argument("--out", default=None, help="output path; '-' = stdout")
    return p.parse_args(argv)


def main(argv=None):
    import yaml

    args = parse_args(argv)
    docs = generate(args)
    text = "---\n".join(yaml.safe_dump(d, sort_keys=False) for d in docs)
    out = args.out or (args.jobname + ".yaml")
    if out == "-":
        sys.stdout.write(text)
    else:
        with open(out, "w") as f:
            f.write(text)
        print("wrote %s (%d manifests)" % (out, len(docs)))
    return docs


if __name__ == "__main__":
    main()
