#!/usr/bin/env python
"""Live training monitor: tail a telemetry JSONL directory into a summary
table.

Reads the per-host shard files (``telemetry-host*.jsonl`` plus rotated
``.1`` siblings) written by paddle_tpu.observability.export.TelemetryExporter
and renders a rolling summary:

    steps/s, p50/p95 step ms, feed-stall %, pipeline bubble (measured vs
    analytic), device memory high-water, compile-cache hits/misses, and the
    resilience health counters.

Usage:
    python tools/monitor.py --dir /path/to/telemetry            # follow
    python tools/monitor.py --dir /path/to/telemetry --once     # one shot
    python tools/monitor.py --dir /path/to/telemetry --window 500
    python tools/monitor.py --dir /path/to/telemetry --watch 2  # clear+redraw
    python tools/monitor.py --fleet_url http://router:port --watch 2

``--fleet_url`` points at a fleet router started with fleet_metrics=True
and renders the fleet-wide section from its ``GET /fleet/stats`` rollup:
per-replica scrape health, the merged (exact, bucket-wise) request
latency percentiles, SLO burn-rate alerts and goodput-vs-roofline gauges.
``--watch N`` clears the screen and re-renders every N seconds, so both
the telemetry table and the fleet section work as a live dashboard.

No dependency on paddle_tpu (pure stdlib) so it can run on a machine that
only has the telemetry files.
"""

import argparse
import glob
import json
import os
import sys
import time
import urllib.request

SHARD_GLOB = "telemetry-host*.jsonl*"


def load_records(telemetry_dir):
    """All records from every host shard (rotated files first), ts-sorted."""
    records = []
    for path in sorted(glob.glob(os.path.join(telemetry_dir, SHARD_GLOB))):
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        records.append(json.loads(line))
                    except ValueError:
                        continue  # torn tail line of a live file
        except OSError:
            continue
    records.sort(key=lambda r: r.get("ts", 0.0))
    return records


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    pos = (q / 100.0) * (len(sorted_vals) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac


def _hist_percentile(rec, q):
    """Percentile estimate from a snapshot histogram record ({buckets,
    counts, count, max}) — same linear-in-bucket interpolation the live
    Histogram.percentile uses, reproduced here so the monitor stays
    stdlib-only."""
    count = rec.get("count") or 0
    if not count:
        return None
    buckets = rec.get("buckets") or []
    counts = rec.get("counts") or []
    hmax = rec.get("max")
    target = count * q / 100.0
    cum = 0
    lo = 0.0
    for i, ub in enumerate(buckets):
        prev = cum
        cum += counts[i]
        if cum >= target:
            est = lo + (target - prev) / max(counts[i], 1) * (ub - lo)
            return min(est, hmax) if hmax is not None else est
        lo = ub
    return hmax


def _serving_summary(metrics):
    """Per-model serving stats from a snapshot's metric dump: {model:
    {p50/p99 latency, queue p50, device p50, fill, rows, padded, outcome
    counts, traces, variants}} keyed off the serving/<model>/... namespace
    (serving/compile_cache and serving/http are runtime-wide, not models)."""
    models = {}
    for name in metrics:
        parts = name.split("/")
        if len(parts) != 3 or parts[0] != "serving":
            continue
        if parts[1] in ("compile_cache", "http"):
            continue
        models.setdefault(parts[1], {})[parts[2]] = metrics[name]

    def scalar(rec, label=""):
        if not rec or "values" not in rec:
            return None
        vals = rec["values"]
        if label:
            return vals.get(label)
        return vals.get("", sum(vals.values()) if vals else None)

    out = {}
    for model, m in sorted(models.items()):
        lat = m.get("latency_ms") or {}
        row = {
            "p50_ms": _hist_percentile(lat, 50) if lat else None,
            "p99_ms": _hist_percentile(lat, 99) if lat else None,
            "queue_p50_ms": _hist_percentile(m.get("queue_ms") or {}, 50)
            if m.get("queue_ms") else None,
            "device_p50_ms": _hist_percentile(m.get("device_ms") or {}, 50)
            if m.get("device_ms") else None,
            "queue_rows": scalar(m.get("queue_rows")),
            "inflight_rows": scalar(m.get("inflight_rows")),
            "rows": scalar(m.get("rows")),
            "padded_rows": scalar(m.get("padded_rows")),
            "traces": scalar(m.get("traces")),
            "variants": scalar(m.get("variants")),
            "precision": scalar(m.get("precision")),
            "ok": scalar(m.get("requests"), "outcome=ok"),
            "rejected": scalar(m.get("requests"), "outcome=rejected"),
            "timeout": scalar(m.get("requests"), "outcome=timeout"),
        }
        fill = m.get("batch_fill") or {}
        if fill.get("count"):
            row["fill_mean"] = fill.get("sum", 0.0) / fill["count"]
        if m.get("gen_tokens"):
            # generation models (GenerationEngine/Scheduler namespace)
            row["gen_tokens"] = scalar(m.get("gen_tokens"))
            row["gen_steps"] = scalar(m.get("gen_steps"))
            row["gen_slots_live"] = scalar(m.get("gen_slots_live"))
            row["gen_slot_occupancy"] = scalar(m.get("gen_slot_occupancy"))
            row["gen_kv_pages"] = scalar(m.get("gen_kv_pages_used"))
            row["gen_prefill_chunks"] = scalar(m.get("gen_prefill_chunks"))
            row["gen_prefix_hit_rate"] = scalar(m.get("gen_prefix_hit_rate"))
            row["gen_pages_shared"] = scalar(m.get("gen_pages_shared"))
            row["gen_paged_flash"] = scalar(
                m.get("gen_paged_flash_dispatches")
            )
            row["gen_kv_bytes"] = scalar(m.get("gen_kv_bytes"))
            row["gen_slots_total"] = scalar(m.get("gen_slots_total"))
            for key, hist in (
                ("gen_token", m.get("gen_token_ms")),
                ("gen_ttft", m.get("gen_ttft_ms")),
            ):
                row[key + "_p50_ms"] = (
                    _hist_percentile(hist, 50) if hist else None
                )
                row[key + "_p99_ms"] = (
                    _hist_percentile(hist, 99) if hist else None
                )
        out[model] = row

    cc_hits = scalar(metrics.get("serving/compile_cache/hits"))
    cc_miss = scalar(metrics.get("serving/compile_cache/misses"))
    if out and (cc_hits is not None or cc_miss is not None):
        out["_compile_cache"] = {"hits": cc_hits or 0, "misses": cc_miss or 0}
    return out


def _data_summary(metrics):
    """Data-runtime stats from a snapshot's metric dump: the data/...
    namespace written by paddle_tpu.data.runtime (ring occupancy and
    throughput, per-worker batch counts and busy fractions, restart and
    dedupe counters)."""
    data = {}
    for name in metrics:
        parts = name.split("/")
        if len(parts) == 2 and parts[0] == "data":
            data[parts[1]] = metrics[name]
    if not data:
        return {}

    def scalar(rec):
        if not rec or not rec.get("values"):
            return None
        vals = rec["values"]
        return vals.get("", sum(vals.values()))

    def labelled(rec):
        return (rec or {}).get("values") or {}

    out = {
        "epochs": scalar(data.get("epochs")),
        "ring_occupancy": scalar(data.get("ring_occupancy")),
        "bytes_per_sec": scalar(data.get("bytes_per_sec")),
        "bytes_total": scalar(data.get("bytes_total")),
        "restarts": scalar(data.get("worker_restarts")),
        "dropped_dup": scalar(data.get("batches_dropped_dup")),
        "workers": {},
    }
    busy = labelled(data.get("worker_busy_frac"))
    batches = labelled(data.get("batches_total"))
    for label in sorted(set(busy) | set(batches)):
        wid = label.split("=", 1)[1] if "=" in label else label
        out["workers"][wid] = {
            "busy_frac": busy.get(label),
            "batches": batches.get(label),
        }
    return out


def _embedding_summary(metrics):
    """Sparse-embedding-engine stats from a snapshot's metric dump: the
    embedding/... gauges written at trace time by paddle_tpu.embedding and
    ops/sparse_ops (per-table rows/bytes and the sparse-vs-dense gradient
    wire cost), keyed by the table=... label."""
    fields = {}
    for name in metrics:
        parts = name.split("/")
        if len(parts) == 2 and parts[0] == "embedding":
            fields[parts[1]] = (metrics[name] or {}).get("values") or {}
    if not fields:
        return {}
    tables = {}
    for field, vals in fields.items():
        for label, v in vals.items():
            table = label.split("=", 1)[1] if "=" in label else label or "?"
            tables.setdefault(table, {})[field] = v
    return tables


def _passes_summary(metrics):
    """Graph-pass pipeline stats from a snapshot's metric dump: the
    passes/... namespace written by paddle_tpu.passes.manager — per-pass
    wall ms and op counts (labeled pass=<name>), fusion groups formed, and
    pipeline application counts (labeled pipeline=<spec>)."""
    fields = {}
    for name in metrics:
        parts = name.split("/")
        if len(parts) == 2 and parts[0] == "passes":
            fields[parts[1]] = (metrics[name] or {}).get("values") or {}
    if not fields:
        return {}

    per_pass = {}
    for field in ("applied", "wall_ms", "ops_before", "ops_after",
                  "ops_removed"):
        for label, v in fields.get(field, {}).items():
            pname = label.split("=", 1)[1] if "=" in label else label or "?"
            per_pass.setdefault(pname, {})[field] = v
    out = {"passes": per_pass}
    fg = fields.get("fusion_groups", {})
    if fg:
        out["fusion_groups"] = sum(fg.values())
    pipelines = fields.get("pipelines", {})
    if pipelines:
        out["pipelines"] = {
            (label.split("=", 1)[1] if "=" in label else label): v
            for label, v in pipelines.items()
        }
    return out


def _resilience_summary(metrics):
    """Elastic-runtime stats from a snapshot's metric dump: the
    resilience/... namespace written by paddle_tpu.resilience.async_ckpt
    and .elastic (checkpoint freshness + stall distribution, and the
    survived-event counters: recoveries, rollbacks, preemptions, watchdog
    stalls)."""
    res = {}
    for name in metrics:
        parts = name.split("/")
        if len(parts) == 2 and parts[0] == "resilience":
            res[parts[1]] = metrics[name]
    if not res:
        return {}

    def scalar(rec):
        if not rec or not rec.get("values"):
            return None
        vals = rec["values"]
        return vals.get("", sum(vals.values()))

    out = {
        "last_ckpt_age_s": scalar(res.get("last_ckpt_age_s")),
        "last_ckpt_step": scalar(res.get("last_ckpt_step")),
        "ckpt_commits": scalar(res.get("ckpt_commits")),
        "recoveries": scalar(res.get("recoveries")),
        "rollbacks": scalar(res.get("rollbacks")),
        "preemptions": scalar(res.get("preemptions")),
        "watchdog_stalls": scalar(res.get("watchdog_stalls")),
    }
    hist = res.get("ckpt_stall_ms")
    if hist and hist.get("count"):
        out["stall_count"] = hist["count"]
        out["stall_mean_ms"] = hist["sum"] / max(hist["count"], 1)
        out["stall_max_ms"] = hist.get("max")
        # p95 by linear interpolation inside the containing bucket — the
        # same estimate registry.Histogram.percentile makes live
        target = hist["count"] * 0.95
        cum, lo = 0, 0.0
        buckets, counts = hist.get("buckets", []), hist.get("counts", [])
        p95 = hist.get("max")
        for i, ub in enumerate(buckets):
            prev = cum
            cum += counts[i]
            if cum >= target:
                frac = (target - prev) / max(counts[i], 1)
                p95 = min(lo + frac * (ub - lo), hist.get("max") or ub)
                break
            lo = ub
        out["stall_p95_ms"] = p95
    return out


def _online_summary(metrics):
    """Online-learning loop stats from a snapshot's metric dump: the
    online/... namespace written by paddle_tpu.online (publisher cadence +
    chain length on the trainer side, per-model serving version + staleness
    gauges on the reloader side)."""
    onl = {}
    for name in metrics:
        parts = name.split("/")
        if len(parts) == 2 and parts[0] == "online":
            onl[parts[1]] = metrics[name]
    if not onl:
        return {}

    def scalar(rec):
        if not rec or not rec.get("values"):
            return None
        vals = rec["values"]
        return vals.get("", sum(vals.values()))

    def by_label(rec, key):
        out = {}
        for label, v in ((rec or {}).get("values") or {}).items():
            if label.startswith(key + "="):
                out[label.split("=", 1)[1]] = v
        return out

    out = {
        "published_version": scalar(onl.get("published_version")),
        "delta_chain_len": scalar(onl.get("delta_chain_len")),
        "publishes": by_label(onl.get("publishes"), "kind"),
        "throttled": scalar(onl.get("publish_throttled")),
        "skipped_clean": scalar(onl.get("publish_skipped_clean")),
        "reloads": scalar(onl.get("reloads")),
        "reload_errors": scalar(onl.get("reload_errors")),
        "max_staleness_seconds": scalar(onl.get("max_staleness_seconds")),
        "train_steps": scalar(onl.get("train_steps")),
        "rows_trained": scalar(onl.get("rows_trained")),
    }
    models = {}
    for key in ("serving_version", "serving_staleness_steps",
                "serving_staleness_seconds"):
        for model, v in by_label(onl.get(key), "model").items():
            models.setdefault(model, {})[key] = v
    out["models"] = models
    return out


def _fleet_summary(metrics):
    """Serving-fleet router stats from a snapshot's metric dump: the
    fleet/... namespace written by paddle_tpu.fleet.router — routed request
    outcomes by kind+code, failover retries, hedge launches/wins, circuit
    breaker flips, retry-budget denials, replica routability gauges, and
    the end-to-end routed latency histogram."""
    flt = {}
    for name in metrics:
        parts = name.split("/")
        if len(parts) == 2 and parts[0] == "fleet":
            flt[parts[1]] = metrics[name]
    if not flt:
        return {}

    def scalar(rec):
        if not rec or not rec.get("values"):
            return None
        vals = rec["values"]
        return vals.get("", sum(vals.values()))

    def labelled(rec):
        return (rec or {}).get("values") or {}

    def pairs(label):
        out = {}
        for p in label.split(","):
            if "=" in p:
                k, v = p.split("=", 1)
                out[k] = v
        return out

    requests = labelled(flt.get("requests"))
    total = ok = errors_5xx = 0
    by_kind = {}
    for label, v in requests.items():
        lp = pairs(label)
        code = lp.get("code", "")
        kind = lp.get("kind", "?")
        total += v
        by_kind[kind] = by_kind.get(kind, 0) + v
        if code.startswith("5"):
            errors_5xx += v
        elif code.startswith("2"):
            ok += v

    transitions = labelled(flt.get("breaker_transitions"))
    opens = sum(
        v for label, v in transitions.items()
        if pairs(label).get("to") == "open"
    )

    hedges = labelled(flt.get("hedges"))
    out = {
        "requests": total,
        "ok": ok,
        "errors_5xx": errors_5xx,
        "by_kind": by_kind,
        "retries": scalar(flt.get("retries")),
        "budget_denied": scalar(flt.get("retry_budget_denied")),
        "hedges_launched": sum(
            v for label, v in hedges.items()
            if pairs(label).get("event") == "launched"
        ),
        "hedges_won": sum(
            v for label, v in hedges.items()
            if pairs(label).get("event") == "won"
        ),
        "breaker_opens": opens,
        "replicas_routable": scalar(flt.get("replicas_routable")),
        "replicas_total": scalar(flt.get("replicas_total")),
    }
    lat = flt.get("request_ms")
    if lat and lat.get("count"):
        out["p50_ms"] = _hist_percentile(lat, 50)
        out["p99_ms"] = _hist_percentile(lat, 99)
    return out


def _tracing_summary(metrics):
    """Request-tracing + flight-recorder health from a snapshot's metric
    dump: span throughput by status, the tail-sampling keep/drop split
    (trace/... from observability.tracing) and anomaly bundles written or
    rate-limited away (flightrec/... from observability.flightrec)."""

    def labelled(name):
        return (metrics.get(name) or {}).get("values") or {}

    def by_label(name, key):
        out = {}
        for label, v in labelled(name).items():
            if label.startswith(key + "="):
                out[label.split("=", 1)[1]] = v
        return out

    spans = by_label("trace/spans", "status")
    segments = by_label("trace/segments", "decision")
    bundles = by_label("flightrec/bundles", "reason")
    suppressed = labelled("flightrec/suppressed")
    if not spans and not segments and not bundles:
        return {}
    return {
        "spans_ok": spans.get("ok", 0),
        "spans_error": spans.get("error", 0),
        "segments_kept": segments.get("kept", 0),
        "segments_dropped": segments.get("dropped", 0),
        "bundles": bundles,
        "bundles_suppressed": sum(suppressed.values()),
    }


def summarize(records, window=200):
    """Aggregate the record stream into the monitor's display fields.

    ``window`` bounds how many of the most recent step records feed the
    rate/latency stats; snapshot records always contribute their latest
    gauges/counters regardless of the window.
    """
    steps = [r for r in records if r.get("kind") == "step"]
    snaps = [r for r in records if r.get("kind") == "snapshot"]
    opprofs = [r for r in records if r.get("kind") == "op_profile"]
    recent = steps[-window:]

    summary = {
        "n_records": len(records),
        "n_steps": len(steps),
        "hosts": sorted({r.get("host", 0) for r in records}),
        "last_step": steps[-1]["step"] if steps else None,
        "steps_per_s": None,
        "p50_ms": None,
        "p95_ms": None,
        "stall_pct": None,
        "loss": None,
        "bubble": None,
        "bubble_analytic": None,
        "pp": None,
        "mem_peak_bytes": None,
        "cache_hits": None,
        "cache_misses": None,
        "health": {},
        "top_ops": [],
        "serving": {},
        "data": {},
        "embedding": {},
        "resilience": {},
        "passes": {},
        "online": {},
        "fleet": {},
        "tracing": {},
    }

    if opprofs:
        # latest profile wins; keep the top rows for the display
        last_prof = opprofs[-1]
        summary["top_ops"] = [
            (
                r.get("op", "?"),
                float(r.get("total_ms", 0.0)),
                float(r.get("pct", 0.0)),
            )
            for r in last_prof.get("ops", [])[:5]
        ]

    if recent:
        walls = sorted(float(r.get("wall_ms", 0.0)) for r in recent)
        summary["p50_ms"] = _percentile(walls, 50)
        summary["p95_ms"] = _percentile(walls, 95)
        total_wall = sum(walls)
        total_steps = sum(int(r.get("n_steps", 1)) for r in recent)
        if total_wall > 0:
            summary["steps_per_s"] = total_steps / (total_wall / 1e3)
        total_stall = sum(float(r.get("feed_stall_ms", 0.0)) for r in recent)
        if total_wall > 0:
            summary["stall_pct"] = 100.0 * total_stall / total_wall
        for r in reversed(recent):
            if r.get("loss") is not None:
                summary["loss"] = r["loss"]
                break
        for r in reversed(recent):
            if r.get("pp"):
                summary["pp"] = r["pp"]
                break

    if snaps:
        last = snaps[-1]
        # registry.snapshot() shape: {name: {"kind": ..., "values":
        # {label_str: v}}} for counters/gauges (label_str "" when unlabelled)
        metrics = last.get("metrics", {})

        def _scalar(name):
            rec = metrics.get(name)
            if not rec or "values" not in rec:
                return None
            vals = rec["values"]
            if not vals:
                return None
            return vals.get("", max(vals.values()))

        summary["bubble"] = _scalar("pp/bubble_measured")
        summary["bubble_analytic"] = _scalar("pp/bubble_analytic")
        mem = _scalar("device/mem_peak_bytes")
        if mem is not None:
            summary["mem_peak_bytes"] = mem
        hits = _scalar("compile_cache/hits")
        misses = _scalar("compile_cache/misses")
        summary["cache_hits"] = int(hits) if hits is not None else None
        summary["cache_misses"] = int(misses) if misses is not None else None
        bub = last.get("bubble")
        if summary["bubble"] is None and bub:
            summary["bubble"] = bub.get("bubble")
            summary["bubble_analytic"] = bub.get("analytic")
        summary["serving"] = _serving_summary(metrics)
        if len(snaps) >= 2:
            # tokens/s for generation models: counter delta over the last
            # two snapshots (snapshot gauges carry no rate of their own)
            prev = snaps[-2]
            dt = (last.get("ts") or 0.0) - (prev.get("ts") or 0.0)
            pmet = prev.get("metrics", {})
            for model, row in summary["serving"].items():
                if not isinstance(row, dict) or row.get("gen_tokens") is None:
                    continue
                rec = pmet.get("serving/%s/gen_tokens" % model) or {}
                before = (rec.get("values") or {}).get("", 0.0)
                if dt > 0:
                    row["gen_tokens_per_s"] = max(
                        0.0, (row["gen_tokens"] - before) / dt
                    )
        summary["data"] = _data_summary(metrics)
        summary["embedding"] = _embedding_summary(metrics)
        summary["resilience"] = _resilience_summary(metrics)
        summary["passes"] = _passes_summary(metrics)
        summary["online"] = _online_summary(metrics)
        summary["fleet"] = _fleet_summary(metrics)
        summary["tracing"] = _tracing_summary(metrics)
        summary["health"] = dict(last.get("health", {}))
        memrec = last.get("mem", {})
        if memrec.get("mem_peak_bytes"):
            cur = summary["mem_peak_bytes"] or 0
            summary["mem_peak_bytes"] = max(cur, memrec["mem_peak_bytes"])
    return summary


def _fmt(value, spec="{:.2f}", none="-"):
    return none if value is None else spec.format(value)


def _fmt_bytes(n):
    if n is None:
        return "-"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if n < 1024 or unit == "TiB":
            return "%.1f %s" % (n, unit)
        n /= 1024.0


def render(summary):
    """Summary dict -> multi-line table string."""
    rows = [
        ("step", _fmt(summary["last_step"], "{:d}")),
        ("hosts", ",".join(str(h) for h in summary["hosts"]) or "-"),
        ("steps/s", _fmt(summary["steps_per_s"])),
        ("p50 step ms", _fmt(summary["p50_ms"])),
        ("p95 step ms", _fmt(summary["p95_ms"])),
        ("feed stall %", _fmt(summary["stall_pct"])),
        ("loss", _fmt(summary["loss"], "{:.6g}")),
    ]
    if summary["pp"]:
        rows.append(("pp stages", _fmt(summary["pp"], "{:d}")))
        rows.append(("bubble (measured)", _fmt(summary["bubble"], "{:.3f}")))
        rows.append(
            ("bubble (analytic)", _fmt(summary["bubble_analytic"], "{:.3f}"))
        )
    rows.append(("mem high-water", _fmt_bytes(summary["mem_peak_bytes"])))
    if summary["cache_hits"] is not None or summary["cache_misses"] is not None:
        rows.append(
            (
                "compile cache",
                "%s hit / %s miss"
                % (
                    _fmt(summary["cache_hits"], "{:d}", "0"),
                    _fmt(summary["cache_misses"], "{:d}", "0"),
                ),
            )
        )
    serving = dict(summary.get("serving") or {})
    cc = serving.pop("_compile_cache", None)
    for model, s in sorted(serving.items()):
        outcomes = "%s ok / %s rej / %s to" % (
            _fmt(s.get("ok"), "{:.0f}", "0"),
            _fmt(s.get("rejected"), "{:.0f}", "0"),
            _fmt(s.get("timeout"), "{:.0f}", "0"),
        )
        # precision gauge: 0 = native float variants, 1 = calibrated int8
        # (engine) / int8 KV pools (generation)
        prec = {0.0: "native", 1.0: "int8"}.get(s.get("precision"))
        label = "serve/" + model
        if prec is not None:
            label += " [%s]" % prec
        rows.append((
            label,
            "p50 %s ms p99 %s ms (queue %s + device %s) | %s" % (
                _fmt(s.get("p50_ms")),
                _fmt(s.get("p99_ms")),
                _fmt(s.get("queue_p50_ms")),
                _fmt(s.get("device_p50_ms")),
                outcomes,
            ),
        ))
        rows.append((
            "serve/%s fill" % model,
            "%s mean fill, %s pad rows, depth %s, %s variants (%s traces)" % (
                _fmt(s.get("fill_mean")),
                _fmt(s.get("padded_rows"), "{:.0f}"),
                _fmt(s.get("queue_rows"), "{:.0f}"),
                _fmt(s.get("variants"), "{:.0f}"),
                _fmt(s.get("traces"), "{:.0f}", "0"),
            ),
        ))
        if s.get("gen_tokens") is not None:
            rows.append((
                "serve/gen %s" % model,
                "%s tok (%s tok/s), token p50 %s p99 %s ms, "
                "ttft p50 %s p99 %s ms" % (
                    _fmt(s.get("gen_tokens"), "{:.0f}"),
                    _fmt(s.get("gen_tokens_per_s"), "{:.0f}"),
                    _fmt(s.get("gen_token_p50_ms")),
                    _fmt(s.get("gen_token_p99_ms")),
                    _fmt(s.get("gen_ttft_p50_ms")),
                    _fmt(s.get("gen_ttft_p99_ms")),
                ),
            ))
            rows.append((
                "serve/gen %s kv" % model,
                "occupancy %s (%s slots live), %s kv pages in use, "
                "%s decode steps" % (
                    _fmt(s.get("gen_slot_occupancy")),
                    _fmt(s.get("gen_slots_live"), "{:.0f}"),
                    _fmt(s.get("gen_kv_pages"), "{:.0f}"),
                    _fmt(s.get("gen_steps"), "{:.0f}"),
                ),
            ))
            rows.append((
                "serve/gen %s fastpath" % model,
                "prefix hit %s, %s pages shared, %s prefill chunks, "
                "%s paged-flash lowerings" % (
                    _fmt(s.get("gen_prefix_hit_rate"), "{:.0%}"),
                    _fmt(s.get("gen_pages_shared"), "{:.0f}"),
                    _fmt(s.get("gen_prefill_chunks"), "{:.0f}"),
                    _fmt(s.get("gen_paged_flash"), "{:.0f}", "0"),
                ),
            ))
            if s.get("gen_kv_bytes") is not None:
                storage = {0.0: "fp32", 1.0: "int8"}.get(
                    s.get("precision"), "?"
                )
                rows.append((
                    "serve/gen %s kv-pool" % model,
                    "%s storage, %s resident, %s slots" % (
                        storage,
                        _fmt_bytes(s.get("gen_kv_bytes")),
                        _fmt(s.get("gen_slots_total"), "{:.0f}"),
                    ),
                ))
    if cc:
        rows.append((
            "serve/compile cache",
            "%d hit / %d miss" % (cc["hits"], cc["misses"]),
        ))
    data = summary.get("data") or {}
    if data:
        rows.append((
            "data/ring",
            "occupancy %s, %s/s (%s total), %s epochs" % (
                _fmt(data.get("ring_occupancy")),
                _fmt_bytes(data.get("bytes_per_sec")),
                _fmt_bytes(data.get("bytes_total")),
                _fmt(data.get("epochs"), "{:.0f}"),
            ),
        ))
        workers = data.get("workers") or {}
        if workers:
            per_worker = " ".join(
                "w%s:%s@%s" % (
                    wid,
                    _fmt(w.get("batches"), "{:.0f}"),
                    _fmt(w.get("busy_frac"), "{:.0%}"),
                )
                for wid, w in sorted(
                    workers.items(),
                    key=lambda kv: (len(kv[0]), kv[0]),
                )
            )
            rows.append((
                "data/workers",
                "%d reporting | batches@busy: %s" % (
                    len(workers), per_worker,
                ),
            ))
        if data.get("restarts") or data.get("dropped_dup"):
            rows.append((
                "data/recovery",
                "%s worker restarts, %s dup batches dropped" % (
                    _fmt(data.get("restarts"), "{:.0f}", "0"),
                    _fmt(data.get("dropped_dup"), "{:.0f}", "0"),
                ),
            ))
    for table, e in sorted((summary.get("embedding") or {}).items()):
        rows.append((
            "embedding/" + table,
            "%s rows (%s; %s/shard), %s touched/step" % (
                _fmt(e.get("table_rows"), "{:.0f}"),
                _fmt_bytes(e.get("table_bytes")),
                _fmt_bytes(e.get("table_bytes_per_shard")),
                _fmt(e.get("rows_touched_per_step"), "{:.0f}"),
            ),
        ))
        if e.get("sparse_grad_bytes") or e.get("dense_grad_bytes"):
            sparse_b = e.get("sparse_grad_bytes")
            dense_b = e.get("dense_grad_bytes")
            ratio = (
                dense_b / sparse_b if sparse_b and dense_b else None
            )
            rows.append((
                "embedding/%s grad" % table,
                "%s sparse vs %s dense (%sx saved)" % (
                    _fmt_bytes(sparse_b),
                    _fmt_bytes(dense_b),
                    _fmt(ratio, "{:.0f}"),
                ),
            ))
    res = summary.get("resilience") or {}
    if res:
        rows.append((
            "resilience/ckpt",
            "last @step %s, age %s s (%s committed)" % (
                _fmt(res.get("last_ckpt_step"), "{:.0f}"),
                _fmt(res.get("last_ckpt_age_s"), "{:.1f}"),
                _fmt(res.get("ckpt_commits"), "{:.0f}", "0"),
            ),
        ))
        if res.get("stall_count"):
            rows.append((
                "resilience/ckpt stall",
                "mean %s ms, p95 %s ms, max %s ms over %d saves" % (
                    _fmt(res.get("stall_mean_ms")),
                    _fmt(res.get("stall_p95_ms")),
                    _fmt(res.get("stall_max_ms")),
                    res["stall_count"],
                ),
            ))
        events = "%s recoveries, %s rollbacks, %s preemptions, %s stalls" % (
            _fmt(res.get("recoveries"), "{:.0f}", "0"),
            _fmt(res.get("rollbacks"), "{:.0f}", "0"),
            _fmt(res.get("preemptions"), "{:.0f}", "0"),
            _fmt(res.get("watchdog_stalls"), "{:.0f}", "0"),
        )
        rows.append(("resilience/events", events))
    onl = summary.get("online") or {}
    if onl:
        kinds = onl.get("publishes") or {}
        rows.append((
            "online/publish",
            "v%s live, chain %s deltas (%s bases + %s deltas cut, "
            "%s throttled, %s clean-skips)" % (
                _fmt(onl.get("published_version"), "{:.0f}"),
                _fmt(onl.get("delta_chain_len"), "{:.0f}", "0"),
                _fmt(kinds.get("base"), "{:.0f}", "0"),
                _fmt(kinds.get("delta"), "{:.0f}", "0"),
                _fmt(onl.get("throttled"), "{:.0f}", "0"),
                _fmt(onl.get("skipped_clean"), "{:.0f}", "0"),
            ),
        ))
        if onl.get("train_steps"):
            rows.append((
                "online/stream",
                "%s steps, %s rows trained" % (
                    _fmt(onl.get("train_steps"), "{:.0f}"),
                    _fmt(onl.get("rows_trained"), "{:.0f}"),
                ),
            ))
        for model, m in sorted((onl.get("models") or {}).items()):
            rows.append((
                "online/serve " + model,
                "v%s live, staleness %s steps / %s s (budget %s s); "
                "%s reloads, %s errors" % (
                    _fmt(m.get("serving_version"), "{:.0f}"),
                    _fmt(m.get("serving_staleness_steps"), "{:.0f}", "0"),
                    _fmt(m.get("serving_staleness_seconds"), "{:.1f}", "0"),
                    _fmt(onl.get("max_staleness_seconds"), "{:.0f}"),
                    _fmt(onl.get("reloads"), "{:.0f}", "0"),
                    _fmt(onl.get("reload_errors"), "{:.0f}", "0"),
                ),
            ))
    flt = summary.get("fleet") or {}
    if flt:
        rows.append((
            "fleet/traffic",
            "%s routed (%s ok / %s 5xx), p50 %s ms p99 %s ms" % (
                _fmt(flt.get("requests"), "{:.0f}", "0"),
                _fmt(flt.get("ok"), "{:.0f}", "0"),
                _fmt(flt.get("errors_5xx"), "{:.0f}", "0"),
                _fmt(flt.get("p50_ms")),
                _fmt(flt.get("p99_ms")),
            ),
        ))
        rows.append((
            "fleet/resilience",
            "%s retries (%s budget-denied), hedges %s launched / %s won, "
            "%s breaker opens" % (
                _fmt(flt.get("retries"), "{:.0f}", "0"),
                _fmt(flt.get("budget_denied"), "{:.0f}", "0"),
                _fmt(flt.get("hedges_launched"), "{:.0f}", "0"),
                _fmt(flt.get("hedges_won"), "{:.0f}", "0"),
                _fmt(flt.get("breaker_opens"), "{:.0f}", "0"),
            ),
        ))
        rows.append((
            "fleet/replicas",
            "%s routable of %s registered" % (
                _fmt(flt.get("replicas_routable"), "{:.0f}"),
                _fmt(flt.get("replicas_total"), "{:.0f}"),
            ),
        ))
    trc = summary.get("tracing") or {}
    if trc:
        rows.append((
            "trace/spans",
            "%s ok / %s error, segments %s kept / %s dropped" % (
                _fmt(trc.get("spans_ok"), "{:.0f}", "0"),
                _fmt(trc.get("spans_error"), "{:.0f}", "0"),
                _fmt(trc.get("segments_kept"), "{:.0f}", "0"),
                _fmt(trc.get("segments_dropped"), "{:.0f}", "0"),
            ),
        ))
        if trc.get("bundles") or trc.get("bundles_suppressed"):
            per_reason = " ".join(
                "%s:%d" % (r, int(v))
                for r, v in sorted((trc.get("bundles") or {}).items())
            ) or "-"
            rows.append((
                "trace/flightrec",
                "bundles %s (%s rate-limited)" % (
                    per_reason,
                    _fmt(trc.get("bundles_suppressed"), "{:.0f}", "0"),
                ),
            ))
    passes = summary.get("passes") or {}
    for pname, p in sorted((passes.get("passes") or {}).items()):
        before = p.get("ops_before")
        after = p.get("ops_after")
        rows.append((
            "pass/" + pname,
            "%s ms, ops %s -> %s (%s applications, %s removed)" % (
                _fmt(p.get("wall_ms")),
                _fmt(before, "{:.0f}"),
                _fmt(after, "{:.0f}"),
                _fmt(p.get("applied"), "{:.0f}", "0"),
                _fmt(p.get("ops_removed"), "{:.0f}", "0"),
            ),
        ))
    if passes.get("fusion_groups"):
        rows.append((
            "pass/fusion groups",
            _fmt(passes["fusion_groups"], "{:.0f}"),
        ))
    for name in sorted(summary["health"]):
        rows.append(("health/" + name, str(summary["health"][name])))
    for op, total_ms, pct in summary.get("top_ops", []):
        rows.append(("op/" + op, "%.3f ms (%.1f%%)" % (total_ms, pct)))

    width = max(len(k) for k, _ in rows)
    lines = ["=== telemetry monitor (%d step records) ===" % summary["n_steps"]]
    for key, val in rows:
        lines.append("  %-*s  %s" % (width, key, val))
    return "\n".join(lines)


def fetch_fleet_stats(fleet_url, timeout_s=2.0):
    """GET <fleet_url>/fleet/stats -> parsed JSON, or an {"error": ...}
    record so a router restart only blanks the section, not the monitor."""
    url = fleet_url.rstrip("/") + "/fleet/stats"
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as r:
            return json.loads(r.read().decode())
    except Exception as e:
        return {"error": repr(e)}


def render_fleet(stats):
    """The /fleet/stats rollup -> the fleet-wide dashboard section."""
    lines = ["=== fleet (merged across replicas) ==="]
    if stats.get("error"):
        lines.append("  (unreachable: %s)" % stats["error"])
        return "\n".join(lines)
    rows = []
    targets = stats.get("targets") or {}
    up = sorted(n for n, t in targets.items() if t.get("ok"))
    down = sorted(n for n, t in targets.items() if not t.get("ok"))
    rows.append((
        "scrape",
        "%d/%d targets up%s" % (
            len(up), len(targets),
            (" (down: %s)" % ", ".join(down)) if down else "",
        ),
    ))
    hists = stats.get("histograms") or {}
    for name in ("fleet/request_ms", "serving/latency_ms"):
        h = hists.get(name)
        if h and h.get("count"):
            rows.append((
                name,
                "n %s, p50 %s ms, p90 %s ms, p99 %s ms (exact, merged "
                "buckets)" % (
                    _fmt(h.get("count"), "{:.0f}"),
                    _fmt(h.get("p50")), _fmt(h.get("p90")),
                    _fmt(h.get("p99")),
                ),
            ))
    counters = stats.get("counters") or {}
    req = counters.get("fleet/requests") or {}
    if req.get("total"):
        rows.append(("fleet/requests", _fmt(req["total"], "{:.0f}")))
    gauges = stats.get("gauges") or {}
    gp = gauges.get("slo/goodput_vs_roofline")
    if gp:
        rows.append((
            "goodput vs roofline",
            "%s (min %s across series)" % (
                _fmt(gp.get("mean"), "{:.3f}"), _fmt(gp.get("min"), "{:.3f}"),
            ),
        ))
    slo = stats.get("slo") or {}
    firing = slo.get("firing") or []
    if slo:
        rows.append((
            "slo",
            "%d objectives, %d sentinels, %d alerts FIRING, %s transitions"
            % (
                len(slo.get("slos") or []),
                len(slo.get("sentinels") or []),
                len(firing),
                _fmt(slo.get("events_total"), "{:.0f}", "0"),
            ),
        ))
        for ev in firing:
            rows.append((
                "  ALERT " + str(ev.get("name")),
                "%s since %s (burn %s / %s)" % (
                    ev.get("severity"),
                    time.strftime("%H:%M:%S",
                                  time.localtime(ev.get("ts", 0))),
                    _fmt(ev.get("burn_short"), "{:.1f}"),
                    _fmt(ev.get("burn_long"), "{:.1f}"),
                ),
            ))
    width = max(len(k) for k, _ in rows)
    for key, val in rows:
        lines.append("  %-*s  %s" % (width, key, val))
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", default="", help="FLAGS_telemetry_dir path")
    ap.add_argument("--once", action="store_true", help="print once and exit")
    ap.add_argument(
        "--window", type=int, default=200,
        help="recent step records used for rate/latency stats",
    )
    ap.add_argument(
        "--interval", type=float, default=2.0,
        help="refresh period in seconds when following",
    )
    ap.add_argument(
        "--watch", type=float, default=0.0, metavar="N",
        help="clear the screen and re-render every N seconds "
             "(live-dashboard mode; implies following)",
    )
    ap.add_argument(
        "--fleet_url", default="",
        help="fleet router base URL (Router(fleet_metrics=True)); renders "
             "the merged /fleet/stats section",
    )
    args = ap.parse_args(argv)
    if not (args.dir or args.fleet_url):
        ap.error("need --dir and/or --fleet_url")
    interval = args.watch if args.watch > 0 else args.interval

    while True:
        blocks = []
        if args.dir:
            records = load_records(args.dir)
            if not records:
                blocks.append("(no telemetry records yet in %s)" % args.dir)
            else:
                blocks.append(render(summarize(records, window=args.window)))
        if args.fleet_url:
            blocks.append(render_fleet(fetch_fleet_stats(args.fleet_url)))
        if args.watch > 0 and not args.once:
            sys.stdout.write("\x1b[2J\x1b[H")  # clear + home
        print("\n\n".join(blocks))
        if args.once:
            return 0
        sys.stdout.flush()
        time.sleep(interval)


if __name__ == "__main__":
    sys.exit(main())
