#!/usr/bin/env python
"""Top-k per-op device-time/FLOPs table from op_profile telemetry records.

Renders the ``op_profile`` records written by
paddle_tpu.observability.opprof (device_profile for an xplane trace,
host_profile for FLAGS_profile_ops host events) — the op-level answer to
"where did this step's time go":

    Op                 Count  Total(ms)   Mean(ms)   FLOPs  Bytes    %  Roof%

plus, with ``--rollup``, a per-category rollup ranked by roofline headroom
(busy ms above each category's roofline minimum — the attack-order signal
for kernel substitution; see docs/observability.md).

Input is either a telemetry directory (FLAGS_telemetry_dir — per-host
``telemetry-host*.jsonl`` shards; the LATEST op_profile record wins), a
single JSONL shard, or a JSON file holding one record (e.g. saved from
``device_profile(...)``).

Usage:
    python tools/op_profile.py --dir /path/to/telemetry
    python tools/op_profile.py --file record.json --top 30
    python tools/op_profile.py --dir /path/to/telemetry --json   # raw record

No dependency on paddle_tpu (pure stdlib) so it can run on a machine that
only has the telemetry files.
"""

import argparse
import glob
import json
import os
import sys

SHARD_GLOB = "telemetry-host*.jsonl*"


def _iter_json_lines(path):
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    yield json.loads(line)
                except ValueError:
                    continue  # torn tail line of a live file
    except OSError:
        return


def load_op_profiles(path):
    """All op_profile records from a telemetry dir, a JSONL shard, or a
    plain JSON file, in ts order."""
    records = []
    if os.path.isdir(path):
        for shard in sorted(glob.glob(os.path.join(path, SHARD_GLOB))):
            records.extend(_iter_json_lines(shard))
    else:
        try:
            with open(path) as f:
                text = f.read()
        except OSError as e:
            print("op_profile: cannot read %s: %s" % (path, e), file=sys.stderr)
            return []
        try:
            doc = json.loads(text)
            records = doc if isinstance(doc, list) else [doc]
        except ValueError:
            records = list(_iter_json_lines(path))
    out = [r for r in records if isinstance(r, dict) and r.get("kind") == "op_profile"]
    out.sort(key=lambda r: r.get("ts", 0.0))
    return out


def _fmt_flops(f):
    if not f:
        return "-"
    for unit in ("", "K", "M", "G", "T", "P"):
        if f < 1000 or unit == "P":
            return "%.4g%s" % (f, unit)
        f /= 1000.0


# roofline peaks for the Roof% column / headroom rollup: analytic defaults
# matching tools/mfu_audit.py; a record carrying "peak_tflops"/"peak_bw_gbs"
# (mfu_audit writes the measured-bandwidth variant) overrides them
PEAK_MM_TFLOPS = 192.0
PEAK_BW_GBS = 676.0


def _roofline_ms(row, peak_tflops, peak_bw_gbs):
    """Roofline minimum busy ms for one row — max of the compute leg
    (flops / peak matmul throughput) and the memory leg (bytes / peak HBM
    bandwidth); None when the row carries neither cost."""
    f = row.get("flops", 0) or 0
    b = row.get("bytes", 0) or 0
    if not f and not b:
        return None
    return max(f / (peak_tflops * 1e9), b / (peak_bw_gbs * 1e6))


def _row_roof_pct(r, peak_tflops, peak_bw_gbs):
    roof = _roofline_ms(r, peak_tflops, peak_bw_gbs)
    if roof is None or not r["total_ms"]:
        return "-"
    return "%.1f" % min(100.0 * roof / r["total_ms"], 100.0)


def render_table(record, top=20):
    """Same layout as paddle_tpu.observability.opprof.render_table — kept in
    sync by tests/test_opprof.py so this tool stays paddle_tpu-free. Roof%
    is achieved fraction of the per-row roofline minimum (100 = nothing
    left to win)."""
    peak_tflops = record.get("peak_tflops", PEAK_MM_TFLOPS)
    peak_bw_gbs = record.get("peak_bw_gbs", PEAK_BW_GBS)
    lines = [
        "---------------->    Op Profile (%s)    <----------------"
        % record.get("source", "?"),
        "%-44s %7s %10s %10s %8s %10s %6s %6s"
        % ("Op", "Count", "Total(ms)", "Mean(ms)", "FLOPs", "Bytes", "%",
           "Roof%"),
    ]
    for r in record.get("ops", [])[:top]:
        lines.append(
            "%-44s %7d %10.4f %10.4f %8s %10s %6.2f %6s"
            % (
                r["op"][:44],
                r["count"],
                r["total_ms"],
                r.get("mean_ms", r["total_ms"] / max(r["count"], 1)),
                _fmt_flops(r.get("flops", 0)),
                _fmt_flops(r.get("bytes", 0)),
                r.get("pct", 0.0),
                _row_roof_pct(r, peak_tflops, peak_bw_gbs),
            )
        )
    total = record.get("total_device_ms")
    if total is not None:
        tail = "total device ms: %.4f" % total
        if record.get("step_ms") is not None:
            tail += "   step ms: %.4f   coverage: %.1f%%" % (
                record["step_ms"],
                100.0 * total / record["step_ms"] if record["step_ms"] else 0.0,
            )
        lines.append(tail)
    return "\n".join(lines)


def render_rollup(record, top=10):
    """Category (op type) rollup ranked by roofline HEADROOM — the busy ms
    above each category's roofline minimum, i.e. the time a kernel
    substitution could still win back. Raw ms ranks a category that is big
    but already optimal above one that is smaller but 3x off roofline;
    headroom is the attack-order signal. Rows without cost analysis are
    assumed AT roofline (they claim no headroom)."""
    peak_tflops = record.get("peak_tflops", PEAK_MM_TFLOPS)
    peak_bw_gbs = record.get("peak_bw_gbs", PEAK_BW_GBS)
    cats = {}
    for r in record.get("ops", []):
        c = cats.setdefault(
            r.get("type") or r["op"],
            {"count": 0, "total_ms": 0.0, "roof_ms": 0.0},
        )
        c["count"] += r["count"]
        c["total_ms"] += r["total_ms"]
        roof = _roofline_ms(r, peak_tflops, peak_bw_gbs)
        c["roof_ms"] += min(
            roof if roof is not None else r["total_ms"], r["total_ms"]
        )
    lines = [
        "----------------> Category rollup (by headroom) <----------------",
        "%-28s %7s %10s %12s %12s %6s"
        % ("Category", "Count", "Total(ms)", "Roofline(ms)", "Headroom(ms)",
           "Roof%"),
    ]
    ranked = sorted(
        cats.items(), key=lambda kv: kv[1]["roof_ms"] - kv[1]["total_ms"]
    )
    for name, c in ranked[:top]:
        headroom = c["total_ms"] - c["roof_ms"]
        pct = 100.0 * c["roof_ms"] / c["total_ms"] if c["total_ms"] else 0.0
        lines.append(
            "%-28s %7d %10.4f %12.4f %12.4f %6.1f"
            % (name[:28], c["count"], c["total_ms"], c["roof_ms"], headroom,
               pct)
        )
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--dir", help="FLAGS_telemetry_dir path")
    src.add_argument(
        "--file", help="one JSONL shard or a JSON file holding a record"
    )
    ap.add_argument("--top", type=int, default=20, help="rows to print")
    ap.add_argument(
        "--json", action="store_true",
        help="dump the raw record instead of the table",
    )
    ap.add_argument(
        "--rollup", action="store_true",
        help="append the per-category headroom rollup",
    )
    args = ap.parse_args(argv)

    records = load_op_profiles(args.dir or args.file)
    if not records:
        print(
            "op_profile: no op_profile records in %s (profile a run with "
            "opprof.device_profile / host_profile and FLAGS_telemetry_dir "
            "set)" % (args.dir or args.file),
            file=sys.stderr,
        )
        return 1
    record = records[-1]
    if args.json:
        print(json.dumps(record, indent=2))
    else:
        print(render_table(record, top=args.top))
        if args.rollup:
            print(render_rollup(record, top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
