"""On-chip flash-kernel rate probe (VERDICT r04 item 1 evidence).

Times paddle_tpu's Pallas flash forward and backward at the MFU-bench
attention shape, reports effective TF/s (bench-accounted flops: 4*b*h*t*t*d
fwd, 2x that bwd — the same accounting bench.py's MFU uses), and compares
against (a) XLA's dense attention chain and (b) jax's own TPU flash kernel
(jax.experimental.pallas.ops.tpu.flash_attention) as the hardware-ceiling
probe.

Usage: python tools/flash_probe.py [t] [--causal]
"""

import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


_RTT_MS = None


def _measure_rtt():
    """One-time measurement of the harness's dispatch+fetch round-trip (the
    tunnel adds ~100 ms per call); subtracted from every timed loop call."""
    global _RTT_MS
    if _RTT_MS is None:
        x = jnp.zeros((8, 128), jnp.float32)
        f = jax.jit(lambda x: x.sum())
        np.asarray(f(x))
        samples = []
        for _ in range(5):
            t0 = time.perf_counter()
            np.asarray(f(x))
            samples.append(time.perf_counter() - t0)
        _RTT_MS = min(samples) * 1e3
        print(f"[harness] dispatch+fetch RTT = {_RTT_MS:.1f} ms (subtracted)")
    return _RTT_MS


def bench(fn, q, k, v, iters=96, warmup=2):
    """Time `iters` applications inside ONE jit call: the probe environment's
    per-dispatch tunnel latency (~8 ms) swamps sub-ms kernels, so the loop
    must live on device. The carry threads the output back into q (same
    shape/dtype), creating a data dependence that defeats CSE/LICM."""

    @jax.jit
    def loop(q, k, v):
        def body(qc, _):
            out = fn(qc, k, v)
            if isinstance(out, tuple):
                # consume every output (a corner element forces the whole
                # producing kernel) or XLA DCEs the dk/dv kernel entirely
                out = out[0] + sum(o[:1, :1, :1, :1] for o in out[1:])
            return out.astype(qc.dtype), ()

        qf, _ = jax.lax.scan(body, q, None, length=iters)
        # scalar result: the sync below is a host FETCH (np.asarray) — the
        # only reliable barrier under the tunnel (block_until_ready returns
        # early there) — and it must not pay a bulk-tensor transfer
        return qf.astype(jnp.float32).sum()

    rtt = _measure_rtt()
    for _ in range(warmup):
        np.asarray(loop(q, k, v))
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        np.asarray(loop(q, k, v))
        best = min(best, time.perf_counter() - t0)
    return max(best * 1e3 - rtt, 1e-6) / iters  # ms/iter


def main():
    b, h, d = 8, 16, 128
    t = int(sys.argv[1]) if len(sys.argv) > 1 and sys.argv[1].isdigit() else 1024
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(b, h, t, d), jnp.bfloat16)
    k = jnp.asarray(rng.randn(b, h, t, d), jnp.bfloat16)
    v = jnp.asarray(rng.randn(b, h, t, d), jnp.bfloat16)
    do = jnp.asarray(rng.randn(b, h, t, d), jnp.bfloat16)

    fwd_flops = 4 * b * h * t * t * d  # QK^T + PV, 2 flops/MAC
    bwd_flops = 2 * fwd_flops  # bench accounting (s/p recompute uncounted)

    from paddle_tpu.ops.pallas_kernels import flash_attention

    for causal in ([False, True] if "--causal" not in sys.argv else [True]):
        cf = 0.5 if causal else 1.0  # causal halves the live score area

        def fwd(q, k, v):
            return flash_attention(q, k, v, causal)

        loss = lambda q, k, v: (flash_attention(q, k, v, causal) * do).sum()
        ms_f = bench(fwd, q, k, v)
        ms_g = bench(jax.grad(loss, argnums=(0, 1, 2)), q, k, v)
        print(f"[ours  ] causal={causal} t={t} fwd {ms_f:7.3f} ms "
              f"({cf*fwd_flops/ms_f/1e9:6.1f} TF/s)  "
              f"fwd+bwd {ms_g:7.3f} ms "
              f"({cf*(fwd_flops+bwd_flops)/ms_g/1e9:6.1f} TF/s eff)")

        # dense XLA chain
        def dense(q, k, v):
            s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * (
                d ** -0.5
            )
            if causal:
                mask = jnp.tril(jnp.ones((t, t), bool))
                s = jnp.where(mask, s, -jnp.inf)
            p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
            return jnp.einsum("bhqk,bhkd->bhqd", p, v)

        dense_loss = lambda q, k, v: (dense(q, k, v) * do).sum()
        try:
            ms_df = bench(dense, q, k, v)
            ms_dg = bench(jax.grad(dense_loss, argnums=(0, 1, 2)), q, k, v)
            print(f"[dense ] causal={causal} t={t} fwd {ms_df:7.3f} ms "
                  f"({cf*fwd_flops/ms_df/1e9:6.1f} TF/s)  "
                  f"fwd+bwd {ms_dg:7.3f} ms "
                  f"({cf*(fwd_flops+bwd_flops)/ms_dg/1e9:6.1f} TF/s eff)")
        except Exception as e:
            print(f"[dense ] causal={causal} failed: {e!r}")

        # jax's own TPU flash kernel — hardware-ceiling probe
        try:
            from jax.experimental.pallas.ops.tpu.flash_attention import (
                flash_attention as jax_flash,
            )

            jf = functools.partial(jax_flash, causal=causal, sm_scale=d ** -0.5)
            jf_loss = lambda q, k, v: (jf(q, k, v) * do).sum()
            ms_jf = bench(jf, q, k, v)
            ms_jg = bench(jax.grad(jf_loss, argnums=(0, 1, 2)), q, k, v)
            print(f"[jaxref] causal={causal} t={t} fwd {ms_jf:7.3f} ms "
                  f"({cf*fwd_flops/ms_jf/1e9:6.1f} TF/s)  "
                  f"fwd+bwd {ms_jg:7.3f} ms "
                  f"({cf*(fwd_flops+bwd_flops)/ms_jg/1e9:6.1f} TF/s eff)")
        except Exception as e:
            print(f"[jaxref] causal={causal} failed: {e!r}")


if __name__ == "__main__":
    main()
