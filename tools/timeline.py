#!/usr/bin/env python
"""Convert profiler dumps into chrome://tracing JSON.

Reference analog: tools/timeline.py:36-160 (protobuf profile → chrome trace,
with --profile_path accepting 'name1=path1,name2=path2' to merge traces from
multiple trainers into one timeline under distinct pids).

Usage:
  python tools/timeline.py --profile_path /tmp/profile --timeline_path /tmp/timeline.json
  python tools/timeline.py --profile_path trainer0=/tmp/p0,trainer1=/tmp/p1 ...
Then open chrome://tracing and load the output.
"""

import argparse
import json


def _load(profile_path):
    named = []
    if "=" in profile_path:
        for part in profile_path.split(","):
            name, _, path = part.partition("=")
            named.append((name, path))
    else:
        named.append(("process", profile_path))
    return named


def convert(profile_path, timeline_path):
    trace_events = []
    metadata = []
    for pid, (name, path) in enumerate(_load(profile_path)):
        with open(path) as f:
            dump = json.load(f)
        metadata.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "args": {"name": name},
            }
        )
        for ev in dump["events"]:
            trace_events.append(
                {
                    "name": ev["name"],
                    "cat": "host",
                    "ph": "X",
                    "pid": pid,
                    "tid": ev["tid"] % 100000,
                    "ts": ev["start"] * 1e6,
                    "dur": (ev["end"] - ev["start"]) * 1e6,
                }
            )
    with open(timeline_path, "w") as f:
        json.dump({"traceEvents": metadata + trace_events}, f)
    return len(trace_events)


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--profile_path", required=True)
    ap.add_argument("--timeline_path", required=True)
    args = ap.parse_args()
    n = convert(args.profile_path, args.timeline_path)
    print("wrote %d events to %s" % (n, args.timeline_path))
