#!/usr/bin/env python
"""Convert profiler dumps into chrome://tracing JSON.

Reference analog: tools/timeline.py:36-160 (protobuf profile → chrome trace,
with --profile_path accepting 'name1=path1,name2=path2' to merge traces from
multiple trainers into one timeline under distinct pids).

Beyond the reference: --telemetry_path takes telemetry JSONL files (the
FLAGS_telemetry_dir stream, observability/export.py) and emits chrome-trace
COUNTER tracks ("ph": "C") — step wall ms, feed-stall ms, loss, and device
memory high-water ride as counters under the same trace, so span events and
the step-level health of the run line up on one time axis. The same
name=path,... form merges counters from multiple trainers.

--trace_path takes a FLAGS_trace_dir directory (or one trace-*.jsonl shard)
of distributed request spans (observability/tracing.py) and lays them out as
"ph": "X" lanes — one chrome pid per (host, process), one lane per thread —
so a request's router -> replica -> batcher -> engine hops read as nested
bars across processes. Span tags/events ride in args for the tooltip.

--alerts_path takes the AlertEngine's JSONL event stream
(observability/slo.py, AlertEngine(out_path=...)) and merges each
fire->resolve pair as one bar on a dedicated "slo alerts" track — an alert
window is visually alignable with the request spans inside it. Unresolved
alerts extend to the stream's last timestamp.

Usage:
  python tools/timeline.py --profile_path /tmp/profile --timeline_path /tmp/timeline.json
  python tools/timeline.py --profile_path trainer0=/tmp/p0,trainer1=/tmp/p1 ...
  python tools/timeline.py --profile_path /tmp/profile \
      --telemetry_path /tmp/telem/telemetry-host0.jsonl \
      --timeline_path /tmp/timeline.json
  python tools/timeline.py --trace_path /tmp/traces \
      --alerts_path /tmp/alerts.jsonl --timeline_path /tmp/timeline.json
Then open chrome://tracing and load the output.
"""

import argparse
import json


def _load(profile_path):
    named = []
    if "=" in profile_path:
        for part in profile_path.split(","):
            name, _, path = part.partition("=")
            named.append((name, path))
    else:
        named.append(("process", profile_path))
    return named


def _read_jsonl(path):
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                continue  # torn tail line of a live telemetry file
    return records


def _counter_events(records, pid):
    """Telemetry records → chrome-trace counter events ("ph": "C").

    Counter timestamps are normalized to the stream's earliest ts so the
    tracks start at 0 like the span events (profiler dumps use
    perf_counter times, telemetry uses epoch times — they don't share a
    clock, but each is internally consistent)."""
    out = []
    tss = [r["ts"] for r in records if "ts" in r]
    if not tss:
        return out
    t0 = min(tss)

    def counter(name, ts, value):
        out.append(
            {
                "name": name,
                "ph": "C",
                "pid": pid,
                "ts": (ts - t0) * 1e6,
                "args": {name: value},
            }
        )

    for r in records:
        ts = r.get("ts")
        if ts is None:
            continue
        if r.get("kind") == "step":
            n = max(int(r.get("n_steps", 1)), 1)
            counter("step_ms", ts, float(r.get("wall_ms", 0.0)) / n)
            if r.get("feed_stall_ms"):
                counter("feed_stall_ms", ts, float(r["feed_stall_ms"]))
            if r.get("loss") is not None:
                counter("loss", ts, float(r["loss"]))
        elif r.get("kind") == "snapshot":
            mem = r.get("mem", {})
            if mem.get("mem_peak_bytes"):
                counter("mem_peak_bytes", ts, mem["mem_peak_bytes"])
            bub = r.get("bubble")
            if bub and bub.get("bubble") is not None:
                counter("pp_bubble", ts, bub["bubble"])
    return out


def _op_profile_events(records, pid):
    """The LATEST op_profile record (observability/opprof.py) → one span
    track: each op's total device ms laid end to end in rank order, so the
    chrome-trace bar widths read as the per-op time breakdown. The lane
    carries FLOPs/bytes/% in args for the tooltip."""
    ops = None
    for r in records:
        if r.get("kind") == "op_profile" and r.get("ops"):
            ops = r["ops"]  # later records win: profiles refine over a run
    if not ops:
        return [], None
    out = []
    cursor = 0.0
    for rank, row in enumerate(ops):
        dur_us = float(row.get("total_ms", 0.0)) * 1e3
        if dur_us <= 0:
            continue
        out.append(
            {
                "name": row.get("op", "?"),
                "cat": "op_profile",
                "ph": "X",
                "pid": pid,
                "tid": 0,
                "ts": cursor,
                "dur": dur_us,
                "args": {
                    "rank": rank,
                    "count": row.get("count", 0),
                    "mean_ms": row.get("mean_ms", 0.0),
                    "flops": row.get("flops", 0),
                    "bytes": row.get("bytes", 0),
                    "pct": row.get("pct", 0.0),
                },
            }
        )
        cursor += dur_us
    meta = {
        "name": "thread_name",
        "ph": "M",
        "pid": pid,
        "tid": 0,
        "args": {"name": "op attribution (total device ms, ranked)"},
    }
    return out, meta


def _trace_span_events(spans, pid_base):
    """Distributed request spans (observability/tracing.py shards) →
    chrome-trace "X" lanes: one chrome pid per (host, os pid), one lane per
    thread. Span starts are epoch seconds normalized to the earliest span
    so the fleet's clocks share the trace's zero (they already share wall
    time — the spans were stamped with time.time())."""
    spans = [s for s in spans if s.get("kind") == "span" and "ts" in s]
    if not spans:
        return [], []
    t0 = min(s["ts"] for s in spans)
    procs = {}  # (host, pid) -> chrome pid
    out, meta = [], []
    for s in sorted(spans, key=lambda s: s["ts"]):
        key = (s.get("host", "?"), s.get("pid", 0))
        cpid = procs.get(key)
        if cpid is None:
            cpid = procs[key] = pid_base + len(procs)
            meta.append(
                {
                    "name": "process_name",
                    "ph": "M",
                    "pid": cpid,
                    "args": {"name": "%s:p%s" % key},
                }
            )
        args = {
            "trace": s.get("trace"),
            "span": s.get("span"),
            "parent": s.get("parent"),
            "status": s.get("status"),
        }
        args.update(s.get("tags") or {})
        if s.get("events"):
            args["events"] = s["events"]
        out.append(
            {
                "name": s.get("name", "?"),
                "cat": "trace",
                "ph": "X",
                "pid": cpid,
                "tid": int(s.get("tid", 0)) % 100000,
                "ts": (s["ts"] - t0) * 1e6,
                "dur": max(float(s.get("dur_ms", 0.0)), 0.001) * 1e3,
                "args": args,
            }
        )
    return out, meta


def _alert_events(records, pid, t0=None):
    """AlertEngine JSONL records -> one chrome-trace "X" bar per
    fire->resolve pair, on a dedicated pid ("slo alerts" track) with one
    lane per alert name. `t0` aligns the track with the span track's zero
    when both are drawn (they share wall-clock stamps)."""
    alerts = [r for r in records
              if r.get("kind") == "alert" and "ts" in r]
    if not alerts:
        return [], []
    alerts.sort(key=lambda r: r["ts"])
    if t0 is None:
        t0 = alerts[0]["ts"]
    t_end = alerts[-1]["ts"]
    lanes = {}   # alert name -> tid
    open_ev = {}  # (name, severity) -> fired record
    out = []

    def bar(fired, end_ts, resolved):
        name = str(fired.get("name", "?"))
        tid = lanes.setdefault(name, len(lanes))
        args = {k: v for k, v in fired.items()
                if k not in ("kind", "ts", "series")}
        args["resolved"] = resolved
        out.append(
            {
                "name": "%s [%s]" % (name, fired.get("severity", "?")),
                "cat": "slo_alert",
                "ph": "X",
                "pid": pid,
                "tid": tid,
                "ts": (fired["ts"] - t0) * 1e6,
                "dur": max((end_ts - fired["ts"]), 0.001) * 1e6,
                "args": args,
            }
        )

    for r in alerts:
        key = (r.get("name"), r.get("severity"))
        if r.get("event") == "fired":
            open_ev[key] = r
        elif r.get("event") == "resolved" and key in open_ev:
            bar(open_ev.pop(key), r["ts"], True)
    for fired in open_ev.values():  # never resolved: extend to stream end
        bar(fired, t_end, False)
    meta = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": pid,
            "args": {"name": "slo alerts"},
        }
    ]
    return out, meta


def convert(profile_path, timeline_path, telemetry_path=None,
            trace_path=None, alerts_path=None):
    trace_events = []
    metadata = []
    pid = 0
    if profile_path:
        for pid, (name, path) in enumerate(_load(profile_path)):
            with open(path) as f:
                dump = json.load(f)
            metadata.append(
                {
                    "name": "process_name",
                    "ph": "M",
                    "pid": pid,
                    "args": {"name": name},
                }
            )
            for ev in dump["events"]:
                trace_events.append(
                    {
                        "name": ev["name"],
                        "cat": "host",
                        "ph": "X",
                        "pid": pid,
                        "tid": ev["tid"] % 100000,
                        "ts": ev["start"] * 1e6,
                        "dur": (ev["end"] - ev["start"]) * 1e6,
                    }
                )
        pid += 1
    if telemetry_path:
        named = _load(telemetry_path)
        for off, (name, path) in enumerate(named):
            tpid = pid + off
            metadata.append(
                {
                    "name": "process_name",
                    "ph": "M",
                    "pid": tpid,
                    "args": {"name": name + ":telemetry"},
                }
            )
            records = _read_jsonl(path)
            trace_events.extend(_counter_events(records, tpid))
            # op_profile records get a dedicated span track (per-op device
            # time breakdown) under their own pid, next to the counters
            op_events, op_meta = _op_profile_events(records, tpid + len(named))
            if op_events:
                metadata.append(
                    {
                        "name": "process_name",
                        "ph": "M",
                        "pid": tpid + len(named),
                        "args": {"name": name + ":op_profile"},
                    }
                )
                metadata.append(op_meta)
                trace_events.extend(op_events)
        pid += 2 * len(named)
    span_t0 = None
    if trace_path:
        import os
        import sys

        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        from paddle_tpu.observability import tracing as _tracing

        spans = _tracing.load_spans(trace_path)
        stamps = [s["ts"] for s in spans
                  if s.get("kind") == "span" and "ts" in s]
        if stamps:
            span_t0 = min(stamps)
        span_events, span_meta = _trace_span_events(spans, pid)
        metadata.extend(span_meta)
        trace_events.extend(span_events)
        pid += 1000  # span lanes allocate pids dynamically; jump clear
    if alerts_path:
        alert_events, alert_meta = _alert_events(
            _read_jsonl(alerts_path), pid, t0=span_t0
        )
        metadata.extend(alert_meta)
        trace_events.extend(alert_events)
    with open(timeline_path, "w") as f:
        json.dump({"traceEvents": metadata + trace_events}, f)
    return len(trace_events)


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--profile_path", default="",
                    help="profiler dump(s); optional if --telemetry_path set")
    ap.add_argument("--timeline_path", required=True)
    ap.add_argument("--telemetry_path", default="",
                    help="telemetry JSONL file(s) (name=path,... to merge); "
                         "emitted as chrome-trace counter tracks")
    ap.add_argument("--trace_path", default="",
                    help="FLAGS_trace_dir directory (or one trace-*.jsonl "
                         "shard) of request spans; emitted as per-process "
                         "span lanes")
    ap.add_argument("--alerts_path", default="",
                    help="AlertEngine JSONL event stream (slo.py "
                         "out_path); fire/resolve pairs emitted as an "
                         "'slo alerts' track")
    args = ap.parse_args()
    if not (args.profile_path or args.telemetry_path or args.trace_path
            or args.alerts_path):
        ap.error("need --profile_path, --telemetry_path, --trace_path "
                 "and/or --alerts_path")
    n = convert(args.profile_path, args.timeline_path,
                telemetry_path=args.telemetry_path or None,
                trace_path=args.trace_path or None,
                alerts_path=args.alerts_path or None)
    print("wrote %d events to %s" % (n, args.timeline_path))
