"""Per-HLO MFU gap audit (PROFILE.md round-4).

For a bench training step (transformer / resnet), profiles per-HLO device
time on the real chip, joins each top instruction with its compiled-HLO
definition (shapes, opcode), computes achieved TF/s (matmul/conv/custom-call)
or GB/s (fusions, from operand+result HBM bytes), and — with --probe — runs
an isolated same-shape probe per top instruction to measure that shape's own
ceiling on this chip. The achieved-vs-probe table is the evidence artifact
for the MFU narrative: every top HLO is either at its probe ceiling (chip
cap, not a framework defect) or the gap is a concrete work item.

Usage (on the bench chip):
    python tools/mfu_audit.py transformer [--probe] [--steps 10] [--top 12]
    python tools/mfu_audit.py resnet      [--probe]

Writes audit JSON to MFU_AUDIT_<model>.json and prints a markdown table.

Reference analog: the per-op profiler tables the reference builds from CUPTI
(platform/device_tracer.cc) — here extended with roofline accounting, which
the reference never had.
"""

import argparse
import json
import os
import re
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

PEAK_MM_TFLOPS = 192.0  # measured: single large independent bf16 matmul
PEAK_BW_GBS = 676.0  # measured: large elementwise fusion HBM bandwidth

_SHAPE_RE = re.compile(r"(bf16|f32|f16|s32|s64|u32|u8|s8|pred|u64)\[([\d,]*)\]")
_DTYPE_BYTES = {"bf16": 2, "f16": 2, "f32": 4, "s32": 4, "u32": 4, "s64": 8,
                "u64": 8, "u8": 1, "s8": 1, "pred": 1}


def _parse_shapes(text):
    """All dtype[shape] tokens in an HLO snippet -> [(dtype, dims, bytes)]."""
    out = []
    for m in _SHAPE_RE.finditer(text):
        dt = m.group(1)
        dims = [int(x) for x in m.group(2).split(",") if x] or [1]
        n = 1
        for x in dims:
            n *= x
        out.append((dt, dims, n * _DTYPE_BYTES[dt]))
    return out


_OPCODE_RE = re.compile(r"(?:^| )([a-z][a-z0-9\-_]*)\(")


def _attr_dims(d, attr):
    """Parse `attr={1,2}` from an HLO def -> tuple of ints."""
    m = re.search(attr + r"=\{([\d,]*)\}", d)
    return tuple(int(x) for x in m.group(1).split(",") if x) if m else ()


def _window_field(d, key, default, n):
    """Parse a window sub-field `key=v0xv1...` -> list of n string tokens
    (values may be negative, e.g. backward-conv pads)."""
    m = re.search(key + r"=([-\dx_]+)", d)
    return m.group(1).split("x") if m else [default] * n


class HloIndex:
    """Instruction name -> definition line, with operand-shape lookup and
    per-computation membership (to attribute dot/conv FLOPs inside fusions —
    the TPU backend fuses dots into kOutput fusions, so top-level fusion
    nodes carry the MXU work)."""

    def __init__(self, hlo_text):
        self.defs = {}
        self.members = {}  # computation name -> [instr names]
        cur = None
        for line in hlo_text.splitlines():
            if not line.startswith(" "):
                # computation headers are unindented:
                #   [ENTRY ]%name (params...) -> result {
                cm = re.match(r"(?:ENTRY )?%?([\w.\-]+) \(.*->.*\{\s*$", line)
                cur = cm.group(1) if cm else None
                continue
            m = re.match(r"\s*(?:ROOT )?%?([\w.\-]+) = (.*)", line)
            if m:
                self.defs[m.group(1)] = m.group(2)
                if cur is not None:
                    self.members.setdefault(cur, []).append(m.group(1))

    def line(self, name):
        return self.defs.get(name) or self.defs.get(name.split(".")[0], "")

    def _split(self, name):
        """def -> (result_text, opcode, operand_list_text). The result may be
        a tuple, so the opcode is the first lowercase word directly before a
        '(' (layout tokens like T(8,128) are uppercase; dtypes carry no
        paren)."""
        d = self.line(name)
        m = _OPCODE_RE.search(d)
        if not m:
            return d, "?", ""
        head = d[: m.start()]
        args = d[m.end():].split(")", 1)[0]  # m ends just past the '('
        return head, m.group(1), args

    def result_shapes(self, name):
        head, _, _ = self._split(name)
        return _parse_shapes(head)

    def opcode(self, name):
        return self._split(name)[1]

    def operand_names(self, name):
        _, _, args = self._split(name)
        return re.findall(r"%([\w.\-]+)", args)

    def hbm_bytes(self, name):
        """Result bytes + operand bytes (fusion roofline traffic estimate)."""
        total = sum(b for _, _, b in self.result_shapes(name))
        for op in self.operand_names(name):
            total += sum(b for _, _, b in self.result_shapes(op))
        return total

    def dot_flops(self, name):
        """2 * batch * M * N * K from a dot's operand shapes + dim numbers."""
        d = self.line(name)
        ops = self.operand_names(name)
        if len(ops) < 2:
            return 0
        lhs = self.result_shapes(ops[0])
        rhs = self.result_shapes(ops[1])
        if not lhs or not rhs:
            return 0
        lhs_dims, rhs_dims = lhs[0][1], rhs[0][1]
        lb, lc = _attr_dims(d, "lhs_batch_dims"), _attr_dims(d, "lhs_contracting_dims")
        batch = 1
        for i in lb:
            batch *= lhs_dims[i]
        k = 1
        for i in lc:
            k *= lhs_dims[i]
        m_free = 1
        for i, sz in enumerate(lhs_dims):
            if i not in lb and i not in lc:
                m_free *= sz
        rb, rc = _attr_dims(d, "rhs_batch_dims"), _attr_dims(d, "rhs_contracting_dims")
        n_free = 1
        for i, sz in enumerate(rhs_dims):
            if i not in rb and i not in rc:
                n_free *= sz
        return 2 * batch * m_free * n_free * k

    def instr_flops(self, name):
        """FLOPs of this instruction: dot/conv directly, or the sum over
        dots/convs inside the called fused computation(s), recursively."""
        op = self.opcode(name)
        if op == "dot":
            return self.dot_flops(name)
        if op == "convolution":
            return self.conv_flops(name)
        if op == "fusion":
            m = re.search(r"calls=%([\w.\-]+)", self.line(name))
            if not m:
                return 0
            return sum(
                self.instr_flops(n)
                for n in self.members.get(m.group(1), [])
                if self.opcode(n) in ("dot", "convolution")
            )
        return 0

    def heavy_op_names(self, name):
        """op_name metadata of the dots/convs inside this fusion (who put
        the MXU work here)."""
        out = []
        op = self.opcode(name)
        if op in ("dot", "convolution"):
            m = re.search(r'op_name="([^"]+)"', self.line(name))
            out.append(m.group(1) if m else name)
        elif op == "fusion":
            m = re.search(r"calls=%([\w.\-]+)", self.line(name))
            if m:
                for n in self.members.get(m.group(1), []):
                    if self.opcode(n) in ("dot", "convolution"):
                        out.extend(self.heavy_op_names(n))
        return out

    def conv_flops(self, name):
        """Exact MAC count: 2 * batch * Cout * Cin_rhs * prod_d(valid taps
        summed over output positions). The TPU backend rewrites batched dots
        as windowed convs with pad/reversal tricks, so naive
        out*cin*kernel overcounts — only taps landing on real (non-pad,
        non-dilation-hole) input elements are MACs."""
        d = self.line(name)
        ops = self.operand_names(name)
        res = self.result_shapes(name)
        if len(ops) < 2 or not res:
            return 0
        lhs = self.result_shapes(ops[0])
        rhs = self.result_shapes(ops[1])
        if not rhs or not lhs:
            return 0
        m = re.search(r"dim_labels=([\w?]+)_([\w?]+)->([\w?]+)", d)
        if not m:
            return 0
        lhs_lab, rhs_lab, out_lab = m.groups()
        lhs_dims, rhs_dims, out_dims = lhs[0][1], rhs[0][1], res[0][1]
        try:
            batch = lhs_dims[lhs_lab.index("b")]
            cin = rhs_dims[rhs_lab.index("i")]
            cout = rhs_dims[rhs_lab.index("o")]
        except ValueError:
            return 0
        n_spatial = len(rhs_lab) - 2
        sizes = [int(x) for x in _window_field(d, "size", "1", n_spatial)]
        strides = [int(x) for x in _window_field(d, "stride", "1", n_spatial)]
        pads = [tuple(int(p) for p in x.split("_")) if "_" in x else (0, 0)
                for x in _window_field(d, "pad", "0_0", n_spatial)]
        lhs_dil = [int(x) for x in _window_field(d, "lhs_dilate", "1", n_spatial)]
        rhs_dil = [int(x) for x in _window_field(d, "rhs_dilate", "1", n_spatial)]

        spatial_macs = 1
        for sd in range(n_spatial):
            lab = str(sd)
            I = lhs_dims[lhs_lab.index(lab)]
            K = rhs_dims[rhs_lab.index(lab)]
            O = out_dims[out_lab.index(lab)]
            if K != sizes[sd]:  # window size is authoritative
                K = sizes[sd]
            ext = (I - 1) * lhs_dil[sd] + 1  # dilated input extent
            s_d = 0
            for o in range(O):
                base = o * strides[sd] - pads[sd][0]
                for k in range(K):
                    pos = base + k * rhs_dil[sd]
                    if 0 <= pos < ext and pos % lhs_dil[sd] == 0:
                        s_d += 1
            spatial_macs *= s_d
        return 2 * batch * cout * cin * spatial_macs



def profile_step(model, steps, b=None, moment_dtype=None):
    """Run the bench step on the chip; return (hlo_text, events, wall_ms).

    events: {instr_name: total_device_ms} summed over `steps` steps."""
    import jax

    import bench
    import paddle_tpu.fluid as fluid
    from paddle_tpu import profiler
    from paddle_tpu.executor import Scope, scope_guard
    from paddle_tpu.transpiler.bf16_transpiler import Bf16Transpiler

    if model == "transformer":
        main, startup, feed, loss, flops = bench.build_transformer(
            moment_dtype=moment_dtype
        )
    elif model == "resnet":
        bs = b or 256
        main, startup, loss = bench.build(bs)
        rng = np.random.RandomState(0)
        feed = {
            "img": jax.device_put(rng.randn(bs, 3, 224, 224).astype("float32")),
            "label": jax.device_put(rng.randint(0, 1000, (bs, 1)).astype("int32")),
        }
        flops = None
    else:
        raise SystemExit("unknown model %r" % model)

    exe = fluid.Executor(fluid.TPUPlace())
    with scope_guard(Scope(seed=0)):
        exe.run(startup)
        Bf16Transpiler().transpile(main)
        for _ in range(3):
            (l,) = exe.run(main, feed=feed, fetch_list=[loss.name],
                           return_numpy=False)
        np.asarray(l)
        hlo = exe.compiled_hlo()
        t0 = time.perf_counter()
        for _ in range(steps):
            (l,) = exe.run(main, feed=feed, fetch_list=[loss.name],
                           return_numpy=False)
        np.asarray(l)
        wall_ms = (time.perf_counter() - t0) / steps * 1e3  # untraced wall
        log_dir = tempfile.mkdtemp(prefix="mfu_audit_")
        with profiler.xla_trace(log_dir):
            for _ in range(steps):
                (l,) = exe.run(main, feed=feed, fetch_list=[loss.name],
                               return_numpy=False)
            np.asarray(l)

    events = collect_events(log_dir)
    return hlo, events, wall_ms, flops


def collect_events(log_dir, cleanup=True):
    """{instr: total_device_ms} via the shared profiler helper. Removes the
    trace dir afterwards (probe loops would otherwise pile up multi-MB
    xplane dumps in /tmp)."""
    import shutil

    from paddle_tpu import profiler

    try:
        return {
            name: row[1]
            for name, row in profiler.device_instr_events(log_dir).items()
        }
    finally:
        if cleanup:
            shutil.rmtree(log_dir, ignore_errors=True)


# ---------------------------------------------------------------------------
# probes: isolated same-shape ceiling measurements
# ---------------------------------------------------------------------------


def _device_ms_of(fn, args, iters=8):
    """Total device-busy ms of one call, from a trace around `iters` calls."""
    import jax

    from paddle_tpu import profiler

    out = fn(*args)
    jax.block_until_ready(out)
    np.asarray(jax.tree_util.tree_leaves(out)[0][..., :1])  # force host sync
    log_dir = tempfile.mkdtemp(prefix="mfu_probe_")
    with profiler.xla_trace(log_dir):
        for _ in range(iters):
            out = fn(*args)
        np.asarray(jax.tree_util.tree_leaves(out)[0][..., :1])
    return sum(collect_events(log_dir).values()) / iters


def probe_dot(lhs_shape, rhs_shape, dimension_numbers, dtype, out_dtype):
    """Same-shape dot alone in a jit; returns ms/call (device)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    rng = np.random.RandomState(0)
    a = jnp.asarray(rng.randn(*lhs_shape), dtype)
    bb = jnp.asarray(rng.randn(*rhs_shape), dtype)

    @jax.jit
    def f(a, bb):
        return lax.dot_general(a, bb, dimension_numbers,
                               preferred_element_type=out_dtype)

    return _device_ms_of(f, (a, bb))


def probe_bandwidth(n_bytes):
    """Streaming elementwise probe moving ~n_bytes through HBM; GB/s."""
    import jax
    import jax.numpy as jnp

    n = max(n_bytes // 3 // 2, 1 << 20)  # 2 reads + 1 write of bf16
    x = jnp.ones((n,), jnp.bfloat16)
    y = jnp.ones((n,), jnp.bfloat16)

    @jax.jit
    def f(x, y):
        return x * 1.0001 + y

    ms = _device_ms_of(f, (x, y))
    return (3 * n * 2) / (ms / 1e3) / 1e9


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("model", choices=["transformer", "resnet"])
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--top", type=int, default=12)
    ap.add_argument("--probe", action="store_true",
                    help="run isolated same-shape probes for top dots")
    ap.add_argument("--hlo-out", default=None,
                    help="also write the compiled HLO text here")
    ap.add_argument("--bf16-moments", action="store_true",
                    help="audit the bench-headline Adam(moment_dtype=bf16) step")
    ap.add_argument("--analytic-bw", action="store_true",
                    help="skip the HBM memcpy microbench and use the "
                         "analytic PEAK_BW_GBS for the memory roofline")
    ap.add_argument("--pass-pipeline", default=None,
                    help="graph-pass pipeline for the audited step (e.g. "
                         "training_fused); default leaves FLAGS_pass_pipeline "
                         "as-is")
    args = ap.parse_args(argv)
    if args.bf16_moments and args.model != "transformer":
        ap.error("--bf16-moments only applies to the transformer step")
    if args.pass_pipeline is not None:
        from paddle_tpu import flags as _flags

        _flags.set_flags({"pass_pipeline": args.pass_pipeline})

    hlo, events, wall_ms, flops = profile_step(
        args.model, args.steps,
        moment_dtype="bfloat16" if args.bf16_moments else None,
    )
    if args.hlo_out:
        with open(args.hlo_out, "w") as f:
            f.write(hlo)
    idx = HloIndex(hlo)
    busy_ms = sum(events.values()) / args.steps

    # ground the memory roofline in THIS chip's measured HBM bandwidth (the
    # memcpy microbench) instead of the analytic constant; measured_bw_gbs
    # had been a null placeholder in r05-era audits
    measured_bw = None
    if not args.analytic_bw:
        try:
            measured_bw = round(probe_bandwidth(1 << 30), 0)
        except Exception as e:
            print("bandwidth probe failed (%r); using analytic PEAK_BW_GBS"
                  % (e,), file=sys.stderr)
    bw_gbs = measured_bw or PEAK_BW_GBS

    rows = []
    tot_fl = tot_bytes = tot_est = 0.0
    for name, tot in sorted(events.items(), key=lambda kv: -kv[1]):
        ms = tot / args.steps
        d = idx.line(name)
        opcode = idx.opcode(name)
        fl = idx.instr_flops(name)
        nbytes = idx.hbm_bytes(name)
        # roofline: overlapped MXU + HBM model against this chip's measured
        # ceilings (matmul probe constant + the bandwidth microbench above)
        est_ms = max(fl / PEAK_MM_TFLOPS / 1e9, nbytes / bw_gbs / 1e6)
        tot_fl += fl
        tot_bytes += nbytes
        tot_est += est_ms
        rows.append({
            "instr": name, "opcode": opcode, "ms_per_step": round(ms, 3),
            "pct_busy": round(100 * ms / busy_ms, 1) if busy_ms else 0,
            "tflops": round(fl / (ms / 1e3) / 1e12, 1) if fl and ms else None,
            "gbs": round(nbytes / (ms / 1e3) / 1e9, 0) if ms else None,
            "roofline_ms": round(est_ms, 3),
            "x_roofline": round(ms / est_ms, 2) if est_ms else None,
            "ops": sorted(set(idx.heavy_op_names(name)))[:3],
            "def": d[:160],
        })

    # category roll-up: how the busy time splits
    cats = {}
    for r in rows:
        if r["opcode"] == "custom-call":
            # the kernel-substitution lowerings scope their calls as
            # "pallas_kernel=<family>.<gid>" (registry._lower_pallas_run);
            # flash attention predates that tag and keeps its legacy label
            m = re.search(r"pallas_kernel=([a-z_0-9]+)", idx.line(r["instr"]))
            c = ("custom-call (pallas %s)" % m.group(1) if m
                 else "custom-call (pallas flash)")
        elif r["tflops"]:
            c = "matmul-bearing fusions"
        elif r["opcode"] in ("fusion",):
            c = "elementwise/reduce fusions"
        else:
            c = r["opcode"]
        e = cats.setdefault(c, [0.0, 0.0, 0.0])  # ms, tflop, gb
        e[0] += r["ms_per_step"]
        e[1] += (r["tflops"] or 0) * r["ms_per_step"] / 1e3
        e[2] += (r["gbs"] or 0) * r["ms_per_step"] / 1e3

    top = rows[: args.top]
    if args.probe:
        for r in top:
            fl = idx.instr_flops(r["instr"])
            if not fl:
                continue
            probe_ms = probe_instr(idx, r["instr"])
            if probe_ms:
                r["probe_ms"] = probe_ms
                r["probe_tflops"] = round(fl / (probe_ms / 1e3) / 1e12, 1)
                r["x_probe"] = round(r["ms_per_step"] / probe_ms, 2)

    out = {
        "model": args.model, "steps": args.steps,
        "wall_ms_per_step": round(wall_ms, 1),
        "device_busy_ms_per_step": round(busy_ms, 1),
        "duty": round(busy_ms / wall_ms, 3),
        "hlo_total_tflops": round(tot_fl / 1e12, 2),
        "hlo_total_gb": round(tot_bytes / 1e9, 2),
        "roofline_min_busy_ms": round(tot_est, 1),
        "busy_x_roofline": round(busy_ms / tot_est, 2) if tot_est else None,
        "measured_bw_gbs": measured_bw,
        "roofline_bw_gbs": bw_gbs,  # which bandwidth grounded the roofline
        "pass_pipeline": args.pass_pipeline,
        "categories": {
            c: {"ms": round(v[0], 1), "tflop": round(v[1], 2),
                "gb": round(v[2], 1)}
            for c, v in sorted(cats.items(), key=lambda kv: -kv[1][0])
        },
        "rows": rows,
    }
    if flops:
        out["counted_tflops_per_step"] = round(flops / 1e12, 2)
        out["achieved_tflops_wall"] = round(flops / (wall_ms / 1e3) / 1e12, 1)
        out["achieved_tflops_busy"] = round(flops / (busy_ms / 1e3) / 1e12, 1)
    path = "MFU_AUDIT_%s.json" % args.model
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({k: v for k, v in out.items() if k != "rows"}))
    fmt = "%-28s %-10s %8s %6s %7s %7s %8s %6s  %s"
    print(fmt % ("instr", "opcode", "ms/step", "%busy", "TF/s", "GB/s",
                 "roof_ms", "x_roof", "ops"))
    for r in top:
        print(fmt % (r["instr"][:28], r["opcode"][:10], r["ms_per_step"],
                     r["pct_busy"], r.get("tflops") or "", r.get("gbs") or "",
                     r["roofline_ms"], r.get("x_roofline") or "",
                     ",".join(o.split("/")[-2] if "/" in o else o for o in r["ops"])[:40]))
    print("wrote", path)


_JDT = {"bf16": "bfloat16", "f32": "float32"}


def probe_instr(idx, name):
    """Isolated same-shape ceiling for the MXU work this instruction (or the
    fusion wrapping it) carries: sum of per-dot/conv probes; ms or None."""
    op = idx.opcode(name)
    if op in ("dot", "convolution"):
        return _probe_one(idx, name)
    if op == "fusion":
        m = re.search(r"calls=%([\w.\-]+)", idx.line(name))
        if not m:
            return None
        total = 0.0
        for n in idx.members.get(m.group(1), []):
            if idx.opcode(n) in ("dot", "convolution"):
                p = _probe_one(idx, n)
                if p is None:
                    return None
                total += p
        return round(total, 3) or None
    return None


def _probe_one(idx, name):
    import jax.numpy as jnp

    d = idx.line(name)
    ops = idx.operand_names(name)
    if len(ops) < 2:
        return None
    lhs = idx.result_shapes(ops[0])
    rhs = idx.result_shapes(ops[1])
    res = idx.result_shapes(name)
    if not (lhs and rhs and res):
        return None
    jdt = {k: getattr(jnp, v) for k, v in _JDT.items()}
    try:
        if idx.opcode(name) == "dot":
            dn = (
                (_attr_dims(d, "lhs_contracting_dims"),
                 _attr_dims(d, "rhs_contracting_dims")),
                (_attr_dims(d, "lhs_batch_dims"),
                 _attr_dims(d, "rhs_batch_dims")),
            )
            return round(
                probe_dot(tuple(lhs[0][1]), tuple(rhs[0][1]), dn,
                          jdt[lhs[0][0]], jdt[res[0][0]]), 3)
        # convolution: matmul-like (2-letter labels) probes as dot_general;
        # spatial convs probe via conv_general_dilated with the same window
        m = re.search(r"dim_labels=([\w?]+)_([\w?]+)->([\w?]+)", d)
        if not m:
            return None
        lhs_lab, rhs_lab, out_lab = m.groups()
        if len(rhs_lab) == 2:  # pure matmul as conv
            dn = (((lhs_lab.index("f"),), (rhs_lab.index("i"),)), ((), ()))
            return round(
                probe_dot(tuple(lhs[0][1]), tuple(rhs[0][1]), dn,
                          jdt[lhs[0][0]], jdt[res[0][0]]), 3)
        return round(
            _probe_conv(d, tuple(lhs[0][1]), tuple(rhs[0][1]),
                        jdt[lhs[0][0]], jdt[rhs[0][0]], jdt[res[0][0]],
                        lhs_lab, rhs_lab, out_lab), 3)
    except Exception as e:
        print("probe failed for %s: %r" % (name, e), file=sys.stderr)
        return None


def _probe_conv(d, lhs_shape, rhs_shape, lhs_dt, rhs_dt, out_dt,
                lhs_lab, rhs_lab, out_lab):
    """Same-shape conv_general_dilated, window attrs parsed from the HLO."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    n_spatial = len(rhs_lab) - 2
    strides = [int(x) for x in _window_field(d, "stride", "1", n_spatial)]
    pads = [tuple(int(p) for p in x.split("_")) if "_" in x else (0, 0)
            for x in _window_field(d, "pad", "0_0", n_spatial)]
    lhs_dil = [int(x) for x in _window_field(d, "lhs_dilate", "1", n_spatial)]
    rhs_dil = [int(x) for x in _window_field(d, "rhs_dilate", "1", n_spatial)]

    def spec(lab):
        # HLO conv labels -> XLA dimension_numbers string: b->N, f->C, i->I,
        # o->O, digits stay
        return "".join(
            {"b": "N", "f": "C", "i": "I", "o": "O"}.get(c, c) for c in lab
        )

    dn = lax.conv_dimension_numbers(
        lhs_shape, rhs_shape, (spec(lhs_lab), spec(rhs_lab), spec(out_lab))
    )
    rng = np.random.RandomState(0)
    a = jnp.asarray(rng.randn(*lhs_shape), lhs_dt)
    w = jnp.asarray(rng.randn(*rhs_shape), rhs_dt)

    @jax.jit
    def f(a, w):
        return lax.conv_general_dilated(
            a, w, strides, pads, lhs_dilation=lhs_dil, rhs_dilation=rhs_dil,
            dimension_numbers=dn, preferred_element_type=out_dt,
        )

    return _device_ms_of(f, (a, w))


if __name__ == "__main__":
    main()
