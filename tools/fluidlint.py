#!/usr/bin/env python
"""fluidlint: run the whole-program static analyzer + checker suite
(paddle_tpu/analysis, docs/static_analysis.md) over a model and print every
finding with op/var provenance.

Usage:
  python tools/fluidlint.py --zoo                 # lint every zoo model
  python tools/fluidlint.py --model lenet         # one model
  python tools/fluidlint.py --model-dir DIR       # a saved inference model
  python tools/fluidlint.py --zoo --json          # machine-readable output
  python tools/fluidlint.py --zoo --strict        # exit 1 on warnings too

Exit code: 0 clean, 1 any error finding (or, with --strict, any finding at
all), 2 usage/build failure. CI runs `--zoo --strict` as a smoke stage
(scripts/build_and_test.sh), so the zoo linting clean is an invariant.

The ZOO registry of `name -> build() -> (program, feed_names, fetch_names)`
is also imported by tests/test_fluidlint.py — the clean-zoo test and this
CLI lint the exact same programs.
"""

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _fresh():
    from paddle_tpu import framework

    return framework.Program(), framework.Program()


def _guard(main, startup):
    import paddle_tpu.fluid as fluid

    class _G:
        def __enter__(self):
            self._u = fluid.unique_name.guard()
            self._p = fluid.program_guard(main, startup)
            self._u.__enter__()
            self._p.__enter__()
            return self

        def __exit__(self, *exc):
            self._p.__exit__(*exc)
            self._u.__exit__(*exc)

    return _G()


def _cv_model(model_fn, img_shape, minimize=False, **kw):
    import paddle_tpu.fluid as fluid

    main, startup = _fresh()
    with _guard(main, startup):
        img = fluid.layers.data(name="img", shape=img_shape, dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        loss, acc = model_fn(img, label, **kw)[:2]
        if minimize:
            fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    # fetch accuracy too: computed-but-unfetched outputs are exactly what
    # the write-never-read checker flags
    return main, ["img", "label"], [loss.name, acc.name]


def build_lenet():
    from paddle_tpu.models import lenet5

    return _cv_model(lenet5, [1, 28, 28], minimize=True)


def build_resnet_cifar10():
    from paddle_tpu.models.resnet import resnet_cifar10

    return _cv_model(resnet_cifar10, [3, 32, 32], depth=20)


def build_vgg16():
    from paddle_tpu.models.vgg import vgg16

    return _cv_model(vgg16, [3, 32, 32], class_num=10)


def build_alexnet():
    from paddle_tpu.models.alexnet import alexnet

    return _cv_model(alexnet, [3, 224, 224], class_dim=10)


def build_googlenet():
    from paddle_tpu.models.googlenet import googlenet

    return _cv_model(googlenet, [3, 224, 224], class_dim=10)


def build_se_resnext50():
    from paddle_tpu.models import se_resnext

    return _cv_model(
        se_resnext.se_resnext50, [3, 64, 64], class_dim=10,
        depth_override=[1, 1, 1, 1], filters_override=[32, 64, 128, 256],
    )


def build_transformer():
    import paddle_tpu.fluid as fluid
    from paddle_tpu.models.transformer import build_tiny_flash_transformer

    main, startup = _fresh()
    with _guard(main, startup):
        feeds, loss = build_tiny_flash_transformer()
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    return main, sorted(feeds), [loss.name]


def build_deepfm():
    import paddle_tpu.fluid as fluid
    from paddle_tpu.models.deepfm import deepfm

    main, startup = _fresh()
    with _guard(main, startup):
        ids = fluid.layers.data(name="ids", shape=[4, 1], dtype="int64")
        label = fluid.layers.data(name="label", shape=[1], dtype="float32")
        loss, pred, _ = deepfm(ids, label, num_features=1000, num_fields=4)
        fluid.optimizer.Adam(learning_rate=5e-3).minimize(loss)
    return main, ["ids", "label"], [loss.name, pred.name]


def build_stacked_lstm():
    import paddle_tpu.fluid as fluid
    from paddle_tpu.models.stacked_lstm import stacked_lstm_net

    main, startup = _fresh()
    with _guard(main, startup):
        words = fluid.layers.data(
            name="words", shape=[1], dtype="int64", lod_level=1
        )
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        loss, acc, _ = stacked_lstm_net(
            words, label, dict_dim=200, emb_dim=16, hid_dim=16, stacked_num=2
        )
    return main, ["words", "label"], [loss.name, acc.name]


def build_machine_translation():
    """NMT training net: recurrent (scan) encoder/decoder sub-blocks."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.models import machine_translation as mt

    B, T, VOCAB = 4, 6, 50
    main, startup = _fresh()
    with _guard(main, startup):
        src = fluid.layers.data(
            name="src", shape=[B, T, 1], dtype="int64", append_batch_size=False
        )
        main.global_block().create_var(
            name="src_len", shape=(B,), dtype="int64"
        )
        src._len_name = "src_len"
        trg = fluid.layers.data(
            name="trg", shape=[B, T + 1, 1], dtype="int64",
            append_batch_size=False,
        )
        lab = fluid.layers.data(
            name="lab", shape=[B, T + 1, 1], dtype="int64",
            append_batch_size=False,
        )
        trg_len = fluid.layers.data(
            name="trg_len", shape=[B], dtype="int64", append_batch_size=False
        )
        loss = mt.train_model(src, trg, lab, trg_len, VOCAB)
        fluid.optimizer.Adam(1e-2).minimize(loss)
    return main, ["src", "src_len", "trg", "lab", "trg_len"], [loss.name]


def build_machine_translation_infer():
    """NMT beam-search decode: while loop, tensor arrays, beam_search_decode
    — the analyzer's hardest control-flow case."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.models import machine_translation as mt

    B, T, VOCAB = 4, 6, 50
    main, startup = _fresh()
    with _guard(main, startup):
        src = fluid.layers.data(
            name="src", shape=[B, T, 1], dtype="int64", append_batch_size=False
        )
        main.global_block().create_var(
            name="src_len", shape=(B,), dtype="int64"
        )
        src._len_name = "src_len"
        ids, scores = mt.infer_model(src, VOCAB)
    return main, ["src", "src_len"], [ids.name, scores.name]


def _gpt():
    from paddle_tpu.models.gpt_decoder import GPTDecoder

    return GPTDecoder(vocab_size=64, n_layer=2, n_head=2, d_model=32,
                      max_context=32)


def build_gpt_forward():
    main, _, feeds, fetches = _gpt().build_forward(2, 8)
    return main, feeds, fetches


def build_gpt_prefill():
    main, _, feeds, fetches = _gpt().build_prefill(8, 4, 8, 32)
    return main, feeds, fetches


def build_gpt_decode():
    main, _, feeds, fetches = _gpt().build_decode(4, 4, 8, 32)
    return main, feeds, fetches


ZOO = {
    "lenet": build_lenet,
    "resnet_cifar10": build_resnet_cifar10,
    "vgg16": build_vgg16,
    "alexnet": build_alexnet,
    "googlenet": build_googlenet,
    "se_resnext50": build_se_resnext50,
    "transformer": build_transformer,
    "deepfm": build_deepfm,
    "stacked_lstm": build_stacked_lstm,
    "machine_translation": build_machine_translation,
    "machine_translation_infer": build_machine_translation_infer,
    "gpt_forward": build_gpt_forward,
    "gpt_prefill": build_gpt_prefill,
    "gpt_decode": build_gpt_decode,
}


def lint_one(name, program, feed_names, fetch_names, as_json=False):
    """Lint one program; returns (analysis, findings) and prints them."""
    from paddle_tpu.analysis import lint_program

    analysis, findings = lint_program(program, feed_names, fetch_names)
    if as_json:
        print(json.dumps({
            "model": name,
            "findings": [
                {
                    "check": f.check, "severity": f.severity,
                    "message": f.message, "var": f.var,
                    "block": f.block_idx, "op_index": f.op_index,
                    "op_type": f.op_type, "op": f.op_display,
                }
                for f in findings
            ],
            "problems": list(analysis.problems),
            "ops_analyzed": len(analysis.records),
        }))
    else:
        tag = "clean" if not findings else "%d finding(s)" % len(findings)
        print("%-28s %s" % (name, tag))
        for f in findings:
            print("  " + f.format())
        for p in analysis.problems:
            print("  note: %s" % (p,))
    return analysis, findings


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="fluidlint", description=__doc__.splitlines()[0]
    )
    ap.add_argument("--model", choices=sorted(ZOO), help="zoo model to lint")
    ap.add_argument("--zoo", action="store_true", help="lint every zoo model")
    ap.add_argument(
        "--model-dir", help="saved inference-model directory to lint"
    )
    ap.add_argument(
        "--strict", action="store_true", help="exit 1 on warnings too"
    )
    ap.add_argument("--json", action="store_true", help="JSONL output")
    args = ap.parse_args(argv)

    targets = []
    if args.zoo:
        targets = sorted(ZOO)
    elif args.model:
        targets = [args.model]
    elif not args.model_dir:
        ap.error("one of --zoo, --model, or --model-dir is required")

    worst = 0
    for name in targets:
        program, feeds, fetches = ZOO[name]()
        _, findings = lint_one(name, program, feeds, fetches, args.json)
        if any(f.severity == "error" for f in findings):
            worst = max(worst, 1)
        elif findings and args.strict:
            worst = max(worst, 1)

    if args.model_dir:
        import paddle_tpu.fluid as fluid
        from paddle_tpu import io as _io
        from paddle_tpu.executor import Executor, Scope, scope_guard

        scope = Scope()
        with scope_guard(scope):
            program, feed_names, fetch_vars = _io.load_inference_model(
                args.model_dir, Executor()
            )
        _, findings = lint_one(
            args.model_dir, program, feed_names,
            [v.name for v in fetch_vars], args.json,
        )
        if any(f.severity == "error" for f in findings):
            worst = max(worst, 1)
        elif findings and args.strict:
            worst = max(worst, 1)

    return worst


if __name__ == "__main__":
    sys.exit(main())
