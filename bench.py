"""Benchmark: ResNet-50 training throughput on one TPU chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "images/sec", "vs_baseline": N}

Baseline: the reference's best published ResNet-50 TRAIN throughput —
84.08 images/sec (bs=256, MKL-DNN, 2-socket Xeon 6148; BASELINE.md /
reference benchmark/IntelOptimizedPaddle.md:38-46). Its GPU tables ship no
ResNet-50 training number, so the CPU MKL-DNN figure is the reference's
headline for this model.
"""

import json
import sys
import time

import numpy as np

BASELINE_IMAGES_PER_SEC = 84.08


def build(batch_size):
    import paddle_tpu.fluid as fluid
    from paddle_tpu import framework
    from paddle_tpu.models import resnet

    main = framework.Program()
    startup = framework.Program()
    with fluid.unique_name.guard():
        with fluid.program_guard(main, startup):
            img = fluid.layers.data(name="img", shape=[3, 224, 224], dtype="float32")
            label = fluid.layers.data(name="label", shape=[1], dtype="int64")
            loss, acc, _ = resnet.resnet50(img, label)
            fluid.optimizer.Momentum(learning_rate=0.1, momentum=0.9).minimize(loss)
    return main, startup, loss


def run(batch_size=256, steps=20, warmup=3, n_staged=4, bf16=True,
        measure_pipeline=True):
    """Synthetic-data throughput, like the reference harness's fake-data mode
    (benchmark/fluid/fluid_benchmark.py): batches are staged on device once and
    cycled, so the headline measures the training step, not this environment's
    host->device tunnel (which is not representative of TPU host bandwidth —
    the real input path is the data layer's async prefetch).

    With measure_pipeline, a second pass feeds through PyReader — host batches
    staged to device by the feeder thread overlapping compute (the real train-
    loop input path, reference operators/reader/buffered_reader.h:48) — and
    the pyreader/staged throughput ratio is reported as pipeline evidence."""
    import jax
    import paddle_tpu.fluid as fluid
    from paddle_tpu.executor import Scope, scope_guard
    from paddle_tpu.py_reader import PyReader

    main, startup, loss = build(batch_size)
    exe = fluid.Executor(fluid.TPUPlace())
    rng = np.random.RandomState(0)
    batches = [
        {
            "img": jax.device_put(
                rng.randn(batch_size, 3, 224, 224).astype("float32")
            ),
            "label": jax.device_put(
                rng.randint(0, 1000, (batch_size, 1)).astype("int32")
            ),
        }
        for _ in range(n_staged)
    ]

    with scope_guard(Scope(seed=0)):
        exe.run(startup)
        if bf16:
            # bfloat16 is the TPU-native training precision (MXU natively
            # multiplies bf16; measured +70%% over f32 on this model). The
            # reference's analog is its float16_transpiler benchmark mode
            # (paddle/contrib/float16).
            from paddle_tpu.transpiler.bf16_transpiler import Bf16Transpiler

            Bf16Transpiler().transpile(main)
        for i in range(warmup):
            (l,) = exe.run(
                main, feed=batches[i % n_staged], fetch_list=[loss.name],
                return_numpy=False,
            )
        np.asarray(l)  # sync
        t0 = time.perf_counter()
        for i in range(steps):
            (l,) = exe.run(
                main, feed=batches[i % n_staged], fetch_list=[loss.name],
                return_numpy=False,
            )
        np.asarray(l)  # sync
        dt = time.perf_counter() - t0
        staged_ips = batch_size * steps / dt
        if not measure_pipeline:
            return staged_ips, None
        try:
            pyreader_ips = _run_pyreader_pass(
                exe, main, loss, batch_size, steps, warmup, n_staged, rng
            )
        except Exception as e:
            # evidence pass must never invalidate the measured headline
            print("pyreader pass failed: %r" % e, file=sys.stderr)
            pyreader_ips = None
    return staged_ips, pyreader_ips


def _run_pyreader_pass(exe, main, loss, batch_size, steps, warmup, n_staged, rng):
    """PyReader-fed pass: fresh host batches each step, async staging."""
    from paddle_tpu.py_reader import PyReader

    host_batches = [
        {
            "img": rng.randn(batch_size, 3, 224, 224).astype("float32"),
            "label": rng.randint(0, 1000, (batch_size, 1)).astype("int32"),
        }
        for _ in range(n_staged)
    ]

    def gen():
        for i in range(steps + warmup):
            yield host_batches[i % n_staged]

    reader = PyReader(["img", "label"], capacity=2)
    reader.decorate_tensor_provider(gen)
    reader.start()
    try:
        it = reader()
        for _ in range(warmup):
            (l,) = exe.run(
                main, feed=next(it), fetch_list=[loss.name], return_numpy=False
            )
        np.asarray(l)
        t0 = time.perf_counter()
        for _ in range(steps):
            (l,) = exe.run(
                main, feed=next(it), fetch_list=[loss.name], return_numpy=False
            )
        np.asarray(l)
        dt = time.perf_counter() - t0
    finally:
        reader.reset()
    return batch_size * steps / dt


def main():
    batch_size = int(sys.argv[1]) if len(sys.argv) > 1 else 256
    ips = pyreader_ips = None
    ladder = [batch_size] + [b for b in (128, 64, 32) if b < batch_size]
    for bs in ladder:  # memory-headroom fallback: strictly smaller sizes only
        try:
            ips, pyreader_ips = run(batch_size=bs)
            break
        except Exception as e:
            print("bench fallback from bs=%d: %r" % (bs, e), file=sys.stderr)
    if ips is None:
        raise SystemExit("all batch sizes failed")
    record = {
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(ips, 2),
        "unit": "images/sec",
        "vs_baseline": round(ips / BASELINE_IMAGES_PER_SEC, 2),
    }
    if pyreader_ips:
        # input-pipeline evidence: PyReader-fed throughput as a fraction of
        # the staged-batch ceiling (target >=0.95 — async staging overlaps
        # the host->device transfer with compute)
        record["pyreader_images_per_sec"] = round(pyreader_ips, 2)
        record["pyreader_frac"] = round(pyreader_ips / ips, 3)
    print(json.dumps(record))


if __name__ == "__main__":
    main()
