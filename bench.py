"""Benchmark: ResNet-50 training throughput on one TPU chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "images/sec", "vs_baseline": N}

Baseline: the reference's best published ResNet-50 TRAIN throughput —
84.08 images/sec (bs=256, MKL-DNN, 2-socket Xeon 6148; BASELINE.md /
reference benchmark/IntelOptimizedPaddle.md:38-46). Its GPU tables ship no
ResNet-50 training number, so the CPU MKL-DNN figure is the reference's
headline for this model.
"""

import json
import os
import sys
import time

import numpy as np

BASELINE_IMAGES_PER_SEC = 84.08


def build(batch_size):
    import paddle_tpu.fluid as fluid
    from paddle_tpu import framework
    from paddle_tpu.models import resnet

    main = framework.Program()
    startup = framework.Program()
    with fluid.unique_name.guard():
        with fluid.program_guard(main, startup):
            img = fluid.layers.data(name="img", shape=[3, 224, 224], dtype="float32")
            label = fluid.layers.data(name="label", shape=[1], dtype="int64")
            loss, acc, _ = resnet.resnet50(img, label)
            fluid.optimizer.Momentum(learning_rate=0.1, momentum=0.9).minimize(loss)
    return main, startup, loss


def run(batch_size=256, steps=32, warmup=3, n_staged=4, bf16=True,
        measure_pipeline=True):
    """Synthetic-data throughput, like the reference harness's fake-data mode
    (benchmark/fluid/fluid_benchmark.py): batches are staged on device once and
    cycled, so the headline measures the training step, not this environment's
    host->device tunnel (which is not representative of TPU host bandwidth —
    the real input path is the data layer's async prefetch).

    With measure_pipeline, a second pass feeds through PyReader — host batches
    staged to device by the feeder thread overlapping compute (the real train-
    loop input path, reference operators/reader/buffered_reader.h:48) — and
    the pyreader/staged throughput ratio is reported as pipeline evidence.

    Timed windows are sized so the single end-of-window fetch sync (~100 ms
    through the bench tunnel) stays under ~3%% of the window — the reference
    harness's steady-state methodology (fluid_benchmark.py:256-291)."""
    import jax
    import paddle_tpu.fluid as fluid
    from paddle_tpu.executor import Scope, scope_guard
    from paddle_tpu.py_reader import PyReader

    main, startup, loss = build(batch_size)
    exe = fluid.Executor(fluid.TPUPlace())
    rng = np.random.RandomState(0)
    batches = [
        {
            "img": jax.device_put(
                rng.randn(batch_size, 3, 224, 224).astype("float32")
            ),
            "label": jax.device_put(
                rng.randint(0, 1000, (batch_size, 1)).astype("int32")
            ),
        }
        for _ in range(n_staged)
    ]

    with scope_guard(Scope(seed=0)):
        exe.run(startup)
        if bf16:
            # bfloat16 is the TPU-native training precision (MXU natively
            # multiplies bf16; measured +70%% over f32 on this model). The
            # reference's analog is its float16_transpiler benchmark mode
            # (paddle/contrib/float16).
            from paddle_tpu.transpiler.bf16_transpiler import Bf16Transpiler

            Bf16Transpiler().transpile(main)
        for i in range(warmup):
            (l,) = exe.run(
                main, feed=batches[i % n_staged], fetch_list=[loss.name],
                return_numpy=False,
            )
        np.asarray(l)  # sync
        t0 = time.perf_counter()
        for i in range(steps):
            (l,) = exe.run(
                main, feed=batches[i % n_staged], fetch_list=[loss.name],
                return_numpy=False,
            )
        np.asarray(l)  # sync
        dt = time.perf_counter() - t0
        single_ips = batch_size * steps / dt

        # multi-step dispatch (the headline): n_staged iterations per XLA
        # call (Executor steps_per_run -> lax.scan with donated state), so
        # the per-call host dispatch cost (~480 state buffers; ~3 ms on the
        # bench tunnel, PROFILE.md "dispatch") is paid once per k steps and
        # wall-clock tracks device-busy time.
        import jax.numpy as jnp

        # k=2*n_staged per call: on-chip sweep showed ~13 ms of per-call
        # host overhead (dispatch + fetch sync), so k=8 holds the step
        # within ~2% of device-busy time while keeping the stacked feed at
        # ~1.2 GB (k x 154 MB for bs=256). If the extra feed memory does
        # not fit, the measured single-dispatch result stands as headline
        # rather than dropping the whole bench to a smaller batch tier.
        try:
            stacked = {
                n: jnp.stack([b[n] for b in batches] * 2) for n in batches[0]
            }
            del batches  # free per-step staged copies before the stacked pass
            k = 2 * n_staged
            calls = max(4, steps // k)
            (l,) = exe.run(
                main, feed=stacked, fetch_list=[loss.name],
                return_numpy=False, steps_per_run=k,
            )  # compile + warm
            np.asarray(l)
            t0 = time.perf_counter()
            for _ in range(calls):
                (l,) = exe.run(
                    main, feed=stacked, fetch_list=[loss.name],
                    return_numpy=False, steps_per_run=k,
                )
            np.asarray(l)  # sync
            dt = time.perf_counter() - t0
            staged_ips = batch_size * k * calls / dt
            del stacked, l  # free ~1.2 GB before the pipeline passes stage
        except Exception as e:
            print("multi-step pass failed, keeping single-dispatch headline: %r"
                  % e, file=sys.stderr)
            staged_ips = single_ips
            stacked = l = None  # free device buffers before pipeline passes
        if not measure_pipeline:
            return staged_ips, single_ips, None, None
        pyreader_ips = pyreader_u8_ips = None
        try:
            pyreader_ips = _run_pyreader_pass(
                exe, main, loss, batch_size, steps, warmup, n_staged, rng
            )
        except Exception as e:
            # evidence pass must never invalidate the measured headline
            print("pyreader pass failed: %r" % e, file=sys.stderr)
        try:
            # compact wire format (VERDICT-4b): uint8 pixels over the link
            # (38.5 MB/step at bs=256 instead of 154 MB), cast to the
            # declared f32/bf16 var dtype ON device, fused into the step
            pyreader_u8_ips = _run_pyreader_pass(
                exe, main, loss, batch_size, steps, warmup, n_staged, rng,
                wire="uint8",
            )
        except Exception as e:
            print("uint8 pyreader pass failed: %r" % e, file=sys.stderr)
    return staged_ips, single_ips, pyreader_ips, pyreader_u8_ips


def _run_pyreader_pass(exe, main, loss, batch_size, steps, warmup, n_staged,
                       rng, wire="float32"):
    """PyReader-fed pass: fresh host batches each step, async staging.
    wire="uint8" feeds raw pixel bytes (4x fewer bytes over the
    host->device link); the executor casts to the declared var dtype on
    device at trace time, fused into the compiled step."""
    from paddle_tpu.py_reader import PyReader

    if wire == "uint8":
        host_batches = [
            {
                "img": rng.randint(
                    0, 256, (batch_size, 3, 224, 224)
                ).astype("uint8"),
                "label": rng.randint(0, 1000, (batch_size, 1)).astype("int32"),
            }
            for _ in range(n_staged)
        ]
    else:
        host_batches = [
            {
                "img": rng.randn(batch_size, 3, 224, 224).astype("float32"),
                "label": rng.randint(0, 1000, (batch_size, 1)).astype("int32"),
            }
            for _ in range(n_staged)
        ]

    def gen():
        for i in range(steps + warmup):
            yield host_batches[i % n_staged]

    reader = PyReader(["img", "label"], capacity=2)
    reader.decorate_tensor_provider(gen)
    reader.start()
    try:
        it = reader()
        for _ in range(warmup):
            (l,) = exe.run(
                main, feed=next(it), fetch_list=[loss.name], return_numpy=False
            )
        np.asarray(l)
        t0 = time.perf_counter()
        for _ in range(steps):
            (l,) = exe.run(
                main, feed=next(it), fetch_list=[loss.name], return_numpy=False
            )
        np.asarray(l)
        dt = time.perf_counter() - t0
    finally:
        reader.reset()
    return batch_size * steps / dt


NOMINAL_BF16_TFLOPS = 197.0  # TPU v5e peak (the bench chip)

# reference's published RNN train number nearest our stacked-LSTM config:
# 2-layer LSTM text-clf, bs=64, hidden=512, t=100, dict=30k → 184 ms/batch
# on K40m (reference benchmark/README.md:113-121)
BASELINE_LSTM_MS_PER_BATCH = 184.0

# reference's best published VGG-19 TRAIN throughput: 30.44 img/s (bs=256,
# MKL-DNN; benchmark/IntelOptimizedPaddle.md:29-37)
BASELINE_VGG19_IMAGES_PER_SEC = 30.44


def run_vgg19(bs=64, steps=30, warmup=3):
    """Tertiary metric: VGG-19 bf16 train (the second model the reference
    publishes a train baseline for)."""
    import jax
    import paddle_tpu.fluid as fluid
    from paddle_tpu import framework
    from paddle_tpu.executor import Scope, scope_guard
    from paddle_tpu.models import vgg

    main, startup = framework.Program(), framework.Program()
    with fluid.unique_name.guard(), fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[3, 224, 224], dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        loss, _, _ = vgg.vgg19(img, label)
        fluid.optimizer.Momentum(learning_rate=0.01, momentum=0.9).minimize(loss)
    exe = fluid.Executor(fluid.TPUPlace())
    rng = np.random.RandomState(0)
    feed = {
        "img": jax.device_put(rng.randn(bs, 3, 224, 224).astype("float32")),
        "label": jax.device_put(rng.randint(0, 1000, (bs, 1)).astype("int64")),
    }
    with scope_guard(Scope(seed=0)):
        exe.run(startup)
        from paddle_tpu.transpiler.bf16_transpiler import Bf16Transpiler

        Bf16Transpiler().transpile(main)
        for _ in range(warmup):
            (l,) = exe.run(main, feed=feed, fetch_list=[loss.name], return_numpy=False)
        np.asarray(l)
        t0 = time.perf_counter()
        for _ in range(steps):
            (l,) = exe.run(main, feed=feed, fetch_list=[loss.name], return_numpy=False)
        np.asarray(l)
        return bs * steps / (time.perf_counter() - t0)


def run_lstm(hid=512, bs=64, t=100, dict_dim=30000, steps=20, warmup=3,
             measure_pipeline=False):
    """Tertiary metric: BASELINE config 5 (stacked dynamic-LSTM text model,
    models/stacked_lstm.py) at the reference's published RNN benchmark shape.
    Full-length sequences (the reference pads to t=100 for its comparison
    too, benchmark/README.md:104)."""
    import jax
    import paddle_tpu.fluid as fluid
    from paddle_tpu import framework
    from paddle_tpu.executor import Scope, scope_guard
    from paddle_tpu.models.stacked_lstm import stacked_lstm_net

    main, startup = framework.Program(), framework.Program()
    with fluid.unique_name.guard(), fluid.program_guard(main, startup):
        words = fluid.layers.data(name="words", shape=[1], dtype="int64", lod_level=1)
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        loss, _, _ = stacked_lstm_net(
            words, label, dict_dim=dict_dim, emb_dim=512, hid_dim=hid,
            stacked_num=2,
        )
        fluid.optimizer.Adam(learning_rate=2e-3).minimize(loss)
    rng = np.random.RandomState(0)
    feed = {
        "words": jax.device_put(rng.randint(0, dict_dim, (bs, t, 1)).astype("int64")),
        "words@LEN": jax.device_put(np.full((bs,), t, "int32")),
        "label": jax.device_put(rng.randint(0, 2, (bs, 1)).astype("int64")),
    }
    exe = fluid.Executor(fluid.TPUPlace())
    with scope_guard(Scope(seed=0)):
        exe.run(startup)
        from paddle_tpu.transpiler.bf16_transpiler import Bf16Transpiler

        Bf16Transpiler().transpile(main)
        # multi-step dispatch: at 18 ms/batch the ~3 ms per-call dispatch is
        # a real fraction; one scan call runs all `steps` batches (token
        # feeds are ~50 KB, stacking is free)
        import jax.numpy as jnp

        stacked = {n: jnp.stack([v] * steps) for n, v in feed.items()}
        for _ in range(warmup // 2 + 1):
            (l,) = exe.run(
                main, feed=stacked, fetch_list=[loss.name],
                return_numpy=False, steps_per_run=steps,
            )
        np.asarray(l)

        # >=5 steady-state supercalls, same methodology as the pyreader pass
        # below: round 4 timed a SINGLE supercall here and a one-off stall in
        # it produced a 93 ms/batch artifact against a ~6 ms steady state
        # (the same run's own pyreader pass proved the skew)
        def _time_staged(timed_calls=5):
            t0 = time.perf_counter()
            for _ in range(timed_calls):
                (l,) = exe.run(
                    main, feed=stacked, fetch_list=[loss.name],
                    return_numpy=False, steps_per_run=steps,
                )
            np.asarray(l)
            return (time.perf_counter() - t0) / (timed_calls * steps) * 1e3

        staged_ms = _time_staged()
        if not measure_pipeline:
            return staged_ms, None

        # Input-pipeline keep-up on a byte-light feed (the VERDICT-4a
        # evidence): this config moves ~51.5 KB/step over the wire (64x100
        # int64 words + lens + labels). BYTE math is easy (~2-3 ms/step at
        # the tunnel's ~20 MB/s) but this harness's tunnel is LATENCY-bound
        # per transfer (~10 ms/device_put x 3 arrays/batch ~= the 11.5 ms
        # step itself — per-step staging measured frac ~0.63). The pipeline
        # design answer is staging granularity: the reader yields SUPER-
        # batches at the steps_per_run granularity (3 transfers per k
        # steps — the reference's double_buffer over paddle.batch batches
        # is the same batching-of-transfers pattern), and next_batch()
        # returns the stacked [k, ...] feed the multi-step call consumes
        # directly.
        from paddle_tpu.py_reader import PyReader

        try:
            host = {
                n: np.stack([np.asarray(v)] * steps) for n, v in feed.items()
            }
            timed_supers = 5

            def gen():
                for _ in range(2 + timed_supers):
                    yield host

            # capacity 2 < timed_supers: the timed window MUST be fed by
            # the producer in steady state (a prestaged-backlog-only pass
            # would be structurally incapable of failing the keep-up bar)
            reader = PyReader(list(feed), capacity=2)
            reader.decorate_tensor_provider(gen)
            reader.start()
            try:
                (l,) = exe.run(
                    main, feed=reader.next_batch(), fetch_list=[loss.name],
                    return_numpy=False, steps_per_run=steps,
                )
                np.asarray(l)
                t0 = time.perf_counter()
                for _ in range(timed_supers):
                    (l,) = exe.run(
                        main, feed=reader.next_batch(),
                        fetch_list=[loss.name],
                        return_numpy=False, steps_per_run=steps,
                    )
                np.asarray(l)
                pyreader_ms = (
                    (time.perf_counter() - t0) / (timed_supers * steps) * 1e3
                )
            finally:
                reader.reset()
            if staged_ms > 1.1 * pyreader_ms:
                # staged (the frac denominator) must sit at or below the
                # producer-fed steady state; a skew here means the staged
                # window caught a stall — remeasure once, then fail loudly
                # rather than emit a nonsense frac (round-4's 14.88)
                print(
                    "lstm staged/pyreader skew %.1f/%.1f ms — remeasuring"
                    % (staged_ms, pyreader_ms), file=sys.stderr,
                )
                staged_ms = min(staged_ms, _time_staged())
            frac = staged_ms / pyreader_ms
            if not 0.0 < frac <= 1.1:
                print(
                    "WARNING: lstm keep-up frac %.2f outside [0, 1.1] — "
                    "staged %.1f ms vs pyreader %.1f ms remains "
                    "inconsistent; reporting the raw value" %
                    (frac, staged_ms, pyreader_ms), file=sys.stderr,
                )
            return staged_ms, frac
        except Exception as e:
            # evidence pass must never invalidate the measured headline
            print("lstm pyreader pass failed: %r" % e, file=sys.stderr)
            return staged_ms, None


def build_transformer(b=8, t=1024, d=2048, n_layer=4, vocab=32000,
                      moment_dtype=None):
    """Build the MFU-bench Transformer train step. Returns
    (main, startup, feed, loss, flops_per_step) with the feed already staged
    on device. Shared by run_transformer_mfu and tools/mfu_audit.py."""
    import jax
    import paddle_tpu.fluid as fluid
    from paddle_tpu import framework
    from paddle_tpu.models import transformer as T

    n_head, d_inner = 16, 4 * d
    main, startup = framework.Program(), framework.Program()
    with fluid.unique_name.guard():
        with fluid.program_guard(main, startup):
            feeds = {}
            for name, shape, dtype in [
                ("src_word", [t], "int64"), ("src_pos", [t], "int64"),
                ("trg_word", [t], "int64"), ("trg_pos", [t], "int64"),
                ("label", [t], "int64"), ("label_weight", [t, 1], "float32"),
            ]:
                feeds[name] = fluid.layers.data(name=name, shape=shape, dtype=dtype)
            loss, _ = T.transformer(
                feeds["src_word"], feeds["src_pos"], feeds["trg_word"],
                feeds["trg_pos"], None, None, None,
                feeds["label"], feeds["label_weight"],
                src_vocab_size=vocab, trg_vocab_size=vocab,
                n_layer=n_layer, n_head=n_head, d_model=d, d_inner=d_inner,
                d_key=d // n_head, d_value=d // n_head,
                dropout=0.0, max_length=t + 1, use_flash=True, padded=False,
            )
            fluid.optimizer.Adam(
                learning_rate=1e-4, moment_dtype=moment_dtype
            ).minimize(loss)

    rng = np.random.RandomState(0)
    pos = np.tile(np.arange(t), (b, 1)).astype("int64")
    feed = {
        "src_word": jax.device_put(rng.randint(0, vocab, (b, t)).astype("int64")),
        "src_pos": jax.device_put(pos),
        "trg_word": jax.device_put(rng.randint(0, vocab, (b, t)).astype("int64")),
        "trg_pos": jax.device_put(pos.copy()),
        "label": jax.device_put(rng.randint(0, vocab, (b, t)).astype("int64")),
        "label_weight": jax.device_put(np.ones((b, t, 1), "float32")),
    }
    enc_mm = n_layer * (4 * d * d + 2 * d * d_inner)
    dec_mm = n_layer * (8 * d * d + 2 * d * d_inner)
    mm = 2 * b * t * (enc_mm + dec_mm) + 2 * b * t * d * vocab
    attn = 4 * b * t * t * d * (3 * n_layer)
    flops = 3 * (mm + attn)
    return main, startup, feed, loss, flops


def run_transformer_mfu(b=8, t=1024, d=2048, n_layer=4, vocab=32000, steps=30,
                        warmup=3, moment_dtype="bfloat16",
                        pass_pipeline=None):
    """Secondary metric: MFU on a compute-dense Transformer train step (the
    north-star metric is MFU, BASELINE.md — ResNet-50 on one v5e chip is
    HBM-bound by its BN/elementwise tier (PROFILE.md), so a matmul-dominated
    model is the honest vehicle for demonstrating MXU utilization). Model:
    enc-dec Transformer (models/transformer.py) with Pallas flash attention,
    bf16, Adam. FLOPs counted as fwd + 2x bwd over the matmul/attention
    terms only (embedding gathers, softmax, norms uncounted)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.executor import Scope, scope_guard

    main, startup, feed, loss, flops = build_transformer(
        b, t, d, n_layer, vocab, moment_dtype=moment_dtype
    )
    import jax.numpy as jnp

    # pass_pipeline (e.g. "training_fused" for the Pallas kernel-substitution
    # tier) applies only to this bench step and is restored on exit
    from contextlib import ExitStack

    from paddle_tpu import flags as _flags

    stack = ExitStack()
    if pass_pipeline is not None:
        prev = _flags.get_flags("pass_pipeline")["pass_pipeline"]
        _flags.set_flags({"pass_pipeline": pass_pipeline})
        stack.callback(lambda: _flags.set_flags({"pass_pipeline": prev}))

    exe = fluid.Executor(fluid.TPUPlace())
    with stack, scope_guard(Scope(seed=0)):
        exe.run(startup)
        from paddle_tpu.transpiler.bf16_transpiler import Bf16Transpiler

        Bf16Transpiler().transpile(main)
        # multi-step dispatch (steps_per_run=32): r04 measured the k-step
        # scan SLOWER here (f32 optimizer-state carry copies); with bf16
        # moments as the default and the r05 flash kernels the scan now
        # beats per-step dispatch (measured 207.2 vs 210.7 ms/step at k=16;
        # k=32 halves the per-call dispatch share again), so it
        # amortizes per-call dispatch + the end-of-window fetch sync the
        # same way the ResNet/LSTM passes do. Each timed window covers 64
        # steps so the single ~100 ms tunnel sync stays under 1%%. The
        # HEADLINE estimator is min-over-windows: the noise here is
        # one-sided — harness contention and stalls only ever ADD time to a
        # window (a one-off host stall once produced a 25%% artifact against
        # the same run's own steady state — the same failure shape as r04's
        # LSTM skew), so the min converges on the device steady state. To
        # make that estimator choice AUDITABLE rather than asserted, >=5
        # windows are timed and every per-window time plus the median ride
        # along in the JSON record: a min far below the median flags a run
        # whose headline deserves suspicion (r05 advisor).
        k = 32
        calls = 2
        windows = 5
        stacked = {n: jnp.stack([v] * k) for n, v in feed.items()}
        window_dts = []
        try:
            (l,) = exe.run(
                main, feed=stacked, fetch_list=[loss.name],
                return_numpy=False, steps_per_run=k,
            )
            np.asarray(l)
            for _ in range(windows):
                t0 = time.perf_counter()
                for _ in range(calls):
                    (l,) = exe.run(
                        main, feed=stacked, fetch_list=[loss.name],
                        return_numpy=False, steps_per_run=k,
                    )
                np.asarray(l)
                window_dts.append((time.perf_counter() - t0) / (calls * k))
        except Exception as e:
            print("transformer multi-step failed, per-step fallback: %r" % e,
                  file=sys.stderr)
            for _ in range(warmup):
                (l,) = exe.run(main, feed=feed, fetch_list=[loss.name],
                               return_numpy=False)
            np.asarray(l)
            window_dts = []
            for _ in range(windows):
                t0 = time.perf_counter()
                for _ in range(max(steps // windows, 1)):
                    (l,) = exe.run(main, feed=feed, fetch_list=[loss.name],
                                   return_numpy=False)
                np.asarray(l)
                window_dts.append(
                    (time.perf_counter() - t0) / max(steps // windows, 1)
                )
    best_dt = min(window_dts)
    median_dt = sorted(window_dts)[len(window_dts) // 2]
    return {
        "tflops_min_window": flops / best_dt / 1e12,
        "tflops_median_window": flops / median_dt / 1e12,
        "window_ms_per_step": [round(dt * 1e3, 2) for dt in window_dts],
    }


def run_zero1_bench(d=512, depth=4, bs_per_dev=16, steps=12, warmup=3):
    """ZeRO-1 vs replicated data parallelism over the local device mesh:
    same MLP+Adam train step under ReduceStrategy.AllReduce (replicated
    optimizer state, gradient all-reduce) and ReduceStrategy.Reduce (ZeRO-1:
    reduce-scatter grad, sharded moments, param all-gather). Reports step
    time for both and the measured PER-CHIP optimizer-state bytes — the
    sharded path's state bytes drop ~dp× (the ZeRO-1 memory claim, measured
    not asserted). Returns None on a single-device harness (the bench chip):
    there is no dp axis to shard over. Wire-volume evidence for the same
    pair of paths comes from tools/comm_audit.py."""
    import jax

    if jax.device_count() < 2:
        return None
    import paddle_tpu.fluid as fluid
    from paddle_tpu import framework
    from paddle_tpu.executor import Scope, scope_guard
    from paddle_tpu.parallel_executor import BuildStrategy, ReduceStrategy

    n_dev = jax.device_count()
    bs = bs_per_dev * n_dev
    rng = np.random.RandomState(0)
    x = rng.randn(bs, d).astype("float32")
    y = rng.randint(0, 10, (bs, 1)).astype("int64")

    def one(strategy):
        main_p, startup = framework.Program(), framework.Program()
        with fluid.unique_name.guard(), fluid.program_guard(main_p, startup):
            xv = fluid.layers.data(name="x", shape=[d], dtype="float32")
            yv = fluid.layers.data(name="y", shape=[1], dtype="int64")
            h = xv
            for _ in range(depth):
                h = fluid.layers.fc(h, size=d, act="relu")
            logits = fluid.layers.fc(h, size=10)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, yv)
            )
            fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
        strat = BuildStrategy()
        strat.reduce_strategy = strategy
        scope = Scope(seed=0)
        with scope_guard(scope):
            fluid.Executor().run(startup)
            pe = fluid.ParallelExecutor(
                loss_name=loss.name, main_program=main_p, build_strategy=strat,
                scope=scope,
            )
            for _ in range(warmup):
                (l,) = pe.run(fetch_list=[loss.name], feed={"x": x, "y": y},
                              return_numpy=False)
            np.asarray(l)
            t0 = time.perf_counter()
            for _ in range(steps):
                (l,) = pe.run(fetch_list=[loss.name], feed={"x": x, "y": y},
                              return_numpy=False)
            np.asarray(l)
            ms = (time.perf_counter() - t0) / steps * 1e3
            # optimizer accumulators carry the unique_name "_acc" suffix
            # (optimizer._add_accumulator); per-chip bytes = device 0's shard
            state_bytes = 0
            for name, val in scope.vars.items():
                if "_acc" in name and hasattr(val, "addressable_shards"):
                    state_bytes += val.addressable_shards[0].data.nbytes
            final_loss = float(np.asarray(l).reshape(-1)[0])
        return ms, state_bytes, final_loss

    ar_ms, ar_bytes, ar_loss = one(ReduceStrategy.AllReduce)
    z1_ms, z1_bytes, z1_loss = one(ReduceStrategy.Reduce)
    assert np.isfinite(z1_loss) and abs(z1_loss - ar_loss) < 5e-2, (
        "zero1 trajectory diverged from replicated: %.4f vs %.4f"
        % (z1_loss, ar_loss)
    )
    return {
        "zero1_devices": n_dev,
        "zero1_step_ms": round(z1_ms, 2),
        "allreduce_step_ms": round(ar_ms, 2),
        "zero1_opt_state_bytes_per_chip": z1_bytes,
        "allreduce_opt_state_bytes_per_chip": ar_bytes,
        "zero1_state_reduction_x": round(ar_bytes / z1_bytes, 2)
        if z1_bytes
        else None,
    }


def run_sharding_bench(d=256, ffn=1024, depth=4, classes=16, bs_per_dev=8,
                       steps=10, warmup=3, smoke=False):
    """Declarative sharding rules (PR 13) evidence pass: the same FFN-block
    transformer stack + Adam trained (a) dp-replicated over all devices and
    (b) under BuildStrategy.sharding_rules on a dp2 x fsdp2 x tp2 mesh —
    Megatron column/row pairs on each block (SpecLayout) with fsdp sharding
    the remaining dims. Measures step time, loss parity, and the PER-CHIP
    param + optimizer-state bytes, asserting the sharded path's resident
    bytes come in at or under 1/fsdp of replicated (the FSDP memory claim;
    with tp2 also splitting the weights the measured factor is ~tp x fsdp).

    Step-time is checked against the analytic projection from the
    comm-audit wire model: at equal global batch the two meshes do the SAME
    per-chip matmul flops ((batch/4) x (params/2) vs (batch/8) x params),
    and on the in-process virtual-device harness wire is memcpy, so the
    projection is the replicated step time itself; the measured ratio is
    recorded and asserted within tolerance.

    Also writes the paper-size analytic HBM projection: the config scaled
    to d=4096/ffn=16384/L=24/vocab=32k whose replicated param+state bytes
    exceed one v5e chip's 16 GB HBM while the tp2 x fsdp2 sharded footprint
    fits — the 'train a model bigger than one chip' claim, with every
    input recorded. Returns None below 8 devices."""
    import jax

    if jax.device_count() < 8:
        return None
    import paddle_tpu.fluid as fluid
    from paddle_tpu import framework
    from paddle_tpu.executor import Scope, scope_guard
    from paddle_tpu.parallel import MeshConfig, SpecLayout
    from paddle_tpu.parallel_executor import BuildStrategy

    if smoke:
        d, ffn, depth, steps = 128, 256, 2, 6
    n_dev = jax.device_count()
    bs = bs_per_dev * n_dev
    rng = np.random.RandomState(0)
    x = rng.randn(bs, d).astype("float32")
    y = rng.randint(0, classes, (bs, 1)).astype("int64")

    def build():
        main_p, startup = framework.Program(), framework.Program()
        with fluid.unique_name.guard(), fluid.program_guard(main_p, startup):
            xv = fluid.layers.data(name="x", shape=[d], dtype="float32")
            yv = fluid.layers.data(name="y", shape=[1], dtype="int64")
            h = xv
            for k in range(depth):
                up = fluid.layers.fc(
                    h, size=ffn, act="relu",
                    param_attr=fluid.ParamAttr(name="blk%d_up.w" % k),
                    bias_attr=fluid.ParamAttr(name="blk%d_up.b" % k),
                )
                down = fluid.layers.fc(
                    up, size=d,
                    param_attr=fluid.ParamAttr(name="blk%d_down.w" % k),
                    bias_attr=fluid.ParamAttr(name="blk%d_down.b" % k),
                )
                h = fluid.layers.elementwise_add(h, down)
            logits = fluid.layers.fc(h, size=classes)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, yv)
            )
            fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
        return main_p, startup, loss

    rules = SpecLayout().transformer_rules(
        column=[r"^blk\d+_up\.w$"],
        row=[r"^blk\d+_down\.w$"],
        vector=[r"^blk\d+_(up|down)\.b$"],
    )

    def one(mesh_cfg, use_rules):
        main_p, startup, loss = build()
        strat = BuildStrategy()
        if use_rules:
            strat.sharding_rules = rules
        scope = Scope(seed=0)
        with scope_guard(scope):
            fluid.Executor().run(startup)
            pe = fluid.ParallelExecutor(
                loss_name=loss.name, main_program=main_p, build_strategy=strat,
                scope=scope, mesh_config=mesh_cfg,
            )
            for _ in range(warmup):
                (l,) = pe.run(fetch_list=[loss.name], feed={"x": x, "y": y},
                              return_numpy=False)
            np.asarray(l)
            t0 = time.perf_counter()
            for _ in range(steps):
                (l,) = pe.run(fetch_list=[loss.name], feed={"x": x, "y": y},
                              return_numpy=False)
            np.asarray(l)
            ms = (time.perf_counter() - t0) / steps * 1e3
            # resident bytes = device 0's shard of every param + accumulator
            param_names = {
                p.name for p in main_p.global_block().all_parameters()
            }
            resident = 0
            for name, val in scope.vars.items():
                if (name in param_names or "_acc" in name) and hasattr(
                    val, "addressable_shards"
                ):
                    resident += val.addressable_shards[0].data.nbytes
            final_loss = float(np.asarray(l).reshape(-1)[0])
        return ms, resident, final_loss

    rep_ms, rep_bytes, rep_loss = one(None, False)  # default: dp over all 8
    shd_ms, shd_bytes, shd_loss = one(
        MeshConfig(dp=2, fsdp=2, tp=2), True
    )
    assert np.isfinite(shd_loss) and abs(shd_loss - rep_loss) < 5e-2, (
        "sharded trajectory diverged from replicated: %.4f vs %.4f"
        % (shd_loss, rep_loss)
    )
    fsdp_size = 2
    assert shd_bytes <= rep_bytes / fsdp_size * 1.1, (
        "sharded per-chip bytes %d exceed replicated/fsdp %d x 1.1"
        % (shd_bytes, rep_bytes // fsdp_size)
    )
    # equal per-chip flops => the projection is the replicated step time;
    # one-sided (faster than projection is fine, CPU timing is noisy)
    assert shd_ms <= rep_ms * 1.15, (
        "sharded step %.2f ms is >15%% over the analytic projection %.2f ms"
        % (shd_ms, rep_ms)
    )

    # paper-size analytic HBM projection (all inputs recorded in the JSON)
    P = dict(d=4096, ffn=16384, depth=24, vocab=32000)
    n_params = (
        P["depth"] * (P["d"] * P["ffn"] * 2 + P["ffn"] + P["d"])
        + P["vocab"] * P["d"]
    )
    # f32 resident training bytes/param: param 4 + two Adam moments 8
    resident_per_param = 12
    hbm_gb = 16.0  # v5e HBM per chip
    replicated_gb = n_params * resident_per_param / 1e9
    sharded_gb = replicated_gb / 4  # tp2 x fsdp2 shards params + state 4x
    assert replicated_gb > hbm_gb > sharded_gb, (
        "paper-size projection no longer straddles one chip's HBM: "
        "replicated %.1f GB, sharded %.1f GB, HBM %.1f GB"
        % (replicated_gb, sharded_gb, hbm_gb)
    )

    return {
        "devices": n_dev,
        "mesh": "dp2 x fsdp2 x tp2 (vs dp%d replicated)" % n_dev,
        "model": "FFN stack d=%d ffn=%d depth=%d, Adam" % (d, ffn, depth),
        "replicated_step_ms": round(rep_ms, 2),
        "sharded_step_ms": round(shd_ms, 2),
        "step_ms_ratio_vs_projection": round(shd_ms / rep_ms, 3),
        "replicated_param_state_bytes_per_chip": rep_bytes,
        "sharded_param_state_bytes_per_chip": shd_bytes,
        "state_reduction_x": round(rep_bytes / shd_bytes, 2) if shd_bytes
        else None,
        "loss_replicated": round(rep_loss, 6),
        "loss_sharded": round(shd_loss, 6),
        "paper_size_projection": {
            "config": P,
            "n_params": n_params,
            "resident_bytes_per_param_f32_adam": resident_per_param,
            "hbm_gb_per_chip_v5e": hbm_gb,
            "replicated_param_state_gb_per_chip": round(replicated_gb, 1),
            "tp2_fsdp2_param_state_gb_per_chip": round(sharded_gb, 1),
            "fits": "sharded only",
        },
    }


def run_pp_bench(dp=2, pp=4, m1=4, m2=16, mb=8, steps=8, warmup=2):
    """Program-level pipeline parallelism (ParallelExecutor + MeshConfig(pp))
    on a dp2×pp4 mesh: an encoder-only Transformer stack pinned one layer
    per stage (framework.device_guard), trained through the GPipe schedule
    at two microbatch counts m1 < m2 with the PER-MICROBATCH size fixed.

    The bubble is MEASURED, not asserted: with t(m) = c + (m+p-1)·τ the
    slope τ = (t(m2)-t(m1))/(m2-m1) is the steady-state per-tick time, so
    bubble(m1) = 1 - m1·τ/t(m1), compared against the analytic GPipe bound
    (p-1)/(m1+p-1) (docs/parallelism.md). A measured/analytic ratio far
    from 1 means the schedule is losing time to something other than
    pipeline fill/drain. Returns None below dp×pp devices."""
    import jax

    if jax.device_count() < dp * pp:
        return None
    import paddle_tpu.fluid as fluid
    from paddle_tpu import framework
    from paddle_tpu.executor import Scope, scope_guard
    from paddle_tpu.models.transformer import encoder_layer
    from paddle_tpu.parallel import MeshConfig
    from paddle_tpu.parallel_executor import BuildStrategy, ExecutionStrategy

    vocab, t_len, d, n_head, d_inner = 512, 16, 64, 4, 256
    n_layer = pp  # one encoder layer per stage

    def build():
        cfg = {"d_key": d // n_head, "d_value": d // n_head, "d_model": d,
               "n_head": n_head, "d_inner": d_inner, "dropout": 0.0}
        word = fluid.layers.data(name="word", shape=[-1, t_len, 1],
                                 dtype="int64", append_batch_size=False)
        pos = fluid.layers.data(name="pos", shape=[-1, t_len, 1],
                                dtype="int64", append_batch_size=False)
        label = fluid.layers.data(name="label", shape=[-1, 1],
                                  dtype="int64", append_batch_size=False)
        with framework.device_guard("pp:0"):
            h = fluid.layers.elementwise_add(
                fluid.layers.embedding(word, size=[vocab, d]),
                fluid.layers.embedding(pos, size=[t_len, d]),
            )
            h = encoder_layer(h, None, cfg)
        for k in range(1, n_layer):
            with framework.device_guard("pp:%d" % k):
                h = encoder_layer(h, None, cfg)
        with framework.device_guard("pp:%d" % (n_layer - 1)):
            pooled = fluid.layers.reduce_mean(h, dim=1)
            logits = fluid.layers.fc(pooled, size=16)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(
                    label=label, logits=logits
                )
            )
        return loss

    def one(m, schedule):
        b = dp * m * mb
        rng = np.random.RandomState(0)
        feed = {
            "word": rng.randint(0, vocab, (b, t_len, 1)).astype("int64"),
            "pos": np.tile(
                np.arange(t_len)[None, :, None], (b, 1, 1)
            ).astype("int64"),
            "label": rng.randint(0, 16, (b, 1)).astype("int64"),
        }
        main_p, startup = framework.Program(), framework.Program()
        with fluid.unique_name.guard(), fluid.program_guard(main_p, startup):
            loss = build()
            fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
        es = ExecutionStrategy()
        es.pipeline_schedule = schedule
        es.num_microbatches = m
        exe = fluid.Executor(fluid.TPUPlace())
        with scope_guard(Scope(seed=0)):
            exe.run(startup)
            pe = fluid.ParallelExecutor(
                loss_name=loss.name, main_program=main_p,
                mesh_config=MeshConfig(dp=dp, pp=pp),
                exec_strategy=es, build_strategy=BuildStrategy(),
            )
            for _ in range(warmup):
                (l,) = pe.run(fetch_list=[loss.name], feed=feed)
            np.asarray(l)
            # min-over-windows: harness noise only ever ADDS time
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                for _ in range(steps):
                    (l,) = pe.run(fetch_list=[loss.name], feed=feed)
                np.asarray(l)
                best = min(best, (time.perf_counter() - t0) / steps)
        return best

    t1 = one(m1, "gpipe")
    t2 = one(m2, "gpipe")
    tau = (t2 - t1) / (m2 - m1)
    measured = 1 - m1 * tau / t1
    analytic = (pp - 1) / (m1 + pp - 1)
    t1_1f1b = one(m1, "1f1b")
    return {
        "pp_mesh": "dp%d x pp%d" % (dp, pp),
        "pp_schedule": "gpipe",
        "pp_microbatch_rows_per_shard": mb,
        "pp_step_ms_m%d" % m1: round(t1 * 1e3, 2),
        "pp_step_ms_m%d" % m2: round(t2 * 1e3, 2),
        "pp_step_ms_m%d_1f1b" % m1: round(t1_1f1b * 1e3, 2),
        "pp_tick_ms": round(tau * 1e3, 3),
        "pp_bubble_measured_m%d" % m1: round(measured, 3),
        "pp_bubble_analytic_m%d" % m1: round(analytic, 3),
        "pp_bubble_measured_over_analytic": round(measured / analytic, 2),
    }


def _save_lenet_inference(model_dir, seed=11):
    """LeNet-class MNIST model -> save_inference_model(model_dir); the
    SERVING bench workload (the serving analog of the book's
    recognize_digits chapter)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu import framework
    from paddle_tpu.executor import Scope, scope_guard

    main, startup = framework.Program(), framework.Program()
    with fluid.unique_name.guard():
        with fluid.program_guard(main, startup):
            img = fluid.layers.data(name="img", shape=[1, 28, 28], dtype="float32")
            conv1 = fluid.layers.conv2d(img, num_filters=6, filter_size=5, padding=2, act="relu")
            pool1 = fluid.layers.pool2d(conv1, pool_size=2, pool_stride=2)
            conv2 = fluid.layers.conv2d(pool1, num_filters=16, filter_size=5, act="relu")
            pool2 = fluid.layers.pool2d(conv2, pool_size=2, pool_stride=2)
            fc1 = fluid.layers.fc(pool2, size=120, act="relu")
            probs = fluid.layers.fc(fc1, size=10, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    with scope_guard(Scope(seed=seed)):
        exe.run(startup)
        fluid.io.save_inference_model(
            model_dir, ["img"], [probs], exe, main_program=main
        )


_COLD_START_CHILD = r"""
import sys, time
model_dir, cache_dir, buckets = sys.argv[1], sys.argv[2], sys.argv[3]
from paddle_tpu.serving import ServingEngine
# timed region: engine build (model load + lowering) + every bucket variant
# acquired and executable — the serving layer's boot-to-warm. Imports are
# identical on both boots and excluded so the ratio measures the cache.
t0 = time.perf_counter()
eng = ServingEngine(model_dir, name="lenet", cache_dir=cache_dir,
                    batch_buckets=tuple(int(b) for b in buckets.split(",")))
eng.warmup()
print("COLD %.4f TRACES %d HITS %d"
      % (time.perf_counter() - t0, eng.traces, eng.cache_hits))
"""


def _cold_start(model_dir, cache_dir, buckets):
    """Boot-to-warm seconds in a FRESH process (in-process jit caches would
    flatter the second boot; a real replica restart pays imports + engine
    build + per-bucket variant acquisition, which is what this times)."""
    import subprocess

    out = subprocess.run(
        [sys.executable, "-c", _COLD_START_CHILD, model_dir, cache_dir,
         ",".join(str(b) for b in buckets)],
        capture_output=True, text=True, timeout=600,
    )
    for line in out.stdout.splitlines():
        if line.startswith("COLD "):
            parts = line.split()
            return float(parts[1]), int(parts[3]), int(parts[5])
    raise RuntimeError(
        "cold-start child failed:\n%s\n%s" % (out.stdout, out.stderr)
    )


def run_serving_bench(duration_s=8.0, clients=4, max_rows=4,
                      offered_interval_ms=4.0):
    """The serving runtime's evidence pass (ISSUE 6 acceptance): sustained
    concurrent load on a LeNet/MNIST-class model through ServingEngine +
    ContinuousBatcher, plus cold-start-from-trace vs cold-start-from-cache
    in fresh subprocesses. Returns the SERVING.json record."""
    import shutil
    import tempfile
    import threading

    from paddle_tpu.observability import registry as _registry
    from paddle_tpu.serving import (
        ContinuousBatcher, QueueFullError, RequestTimeout, ServingEngine,
    )

    tmp = tempfile.mkdtemp(prefix="serving-bench-")
    model_dir = os.path.join(tmp, "lenet")
    cache_dir = os.path.join(tmp, "cache")
    buckets = (1, 2, 4, 8, 16, 32, 64, 128, 256)
    try:
        _save_lenet_inference(model_dir)

        # ---- cold start: trace vs cache, each in a fresh process ----------
        cold_trace, traces1, hits1 = _cold_start(model_dir, cache_dir, buckets)
        assert traces1 == len(buckets) and hits1 == 0, (traces1, hits1)
        cold_cache, traces2, hits2 = _cold_start(model_dir, cache_dir, buckets)
        assert traces2 == 0, "second boot traced %d variants" % traces2

        # ---- sustained concurrent load ------------------------------------
        eng = ServingEngine(
            model_dir, name="lenet", cache_dir=cache_dir, batch_buckets=buckets
        )
        eng.warmup()
        traces_after_warmup = eng.traces
        batcher = ContinuousBatcher(
            eng, max_queue_rows=256, max_batch_delay_ms=2.0, timeout_ms=5000.0
        )
        counts = {"ok": 0, "rejected": 0, "timeout": 0, "error": 0}
        lock = threading.Lock()
        stop_at = time.perf_counter() + duration_s
        rng0 = np.random.RandomState(0)
        payloads = [
            rng0.randn(r, 1, 28, 28).astype("float32")
            for r in range(1, max_rows + 1)
        ]

        def client(k):
            i = 0
            while time.perf_counter() < stop_at:
                feed = {"img": payloads[(k + i) % len(payloads)]}
                i += 1
                try:
                    batcher.run(feed, timeout=30.0)
                    outcome = "ok"
                except QueueFullError:
                    outcome = "rejected"
                except RequestTimeout:
                    outcome = "timeout"
                except Exception:
                    outcome = "error"
                with lock:
                    counts[outcome] += 1
                time.sleep(offered_interval_ms / 1e3)

        t0 = time.perf_counter()
        threads = [
            threading.Thread(target=client, args=(k,)) for k in range(clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        batcher.close(drain=True)

        reg = _registry.default_registry()
        lat = reg.get("serving/lenet/latency_ms")
        queue = reg.get("serving/lenet/queue_ms")
        device = reg.get("serving/lenet/device_ms")
        fill = reg.get("serving/lenet/batch_fill")
        rows = reg.get("serving/lenet/rows")
        padded = reg.get("serving/lenet/padded_rows")
        offered = sum(counts.values())
        served_fraction = counts["ok"] / float(offered) if offered else 0.0
        real_rows = rows.value() if rows else 0
        pad_rows = padded.value() if padded else 0
        record = {
            "metric": "serving_lenet",
            "requests_offered": offered,
            "requests_ok": counts["ok"],
            "requests_rejected": counts["rejected"],
            "requests_timeout": counts["timeout"],
            "requests_error": counts["error"],
            "served_fraction": round(served_fraction, 4),
            "requests_per_sec": round(counts["ok"] / wall, 1),
            "concurrent_clients": clients,
            "offered_interval_ms": offered_interval_ms,
            "p50_latency_ms": round(lat.percentile(50), 3) if lat else None,
            "p99_latency_ms": round(lat.percentile(99), 3) if lat else None,
            "p50_queue_ms": round(queue.percentile(50), 3) if queue else None,
            "p50_device_ms": round(device.percentile(50), 3) if device else None,
            "batch_fill_mean": round(fill._sum / fill.count, 3)
            if fill and fill.count else None,
            "padding_waste_frac": round(
                pad_rows / float(real_rows + pad_rows), 3
            ) if real_rows + pad_rows else None,
            "traces_after_warmup": eng.traces - traces_after_warmup,
            "compile_cache": eng.cache.stats() if eng.cache else None,
            "batch_buckets": list(buckets),
            "cold_start_from_trace_s": round(cold_trace, 3),
            "cold_start_from_cache_s": round(cold_cache, 3),
            "cold_start_speedup_x": round(cold_trace / cold_cache, 2),
        }
        return record
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def run_generation_bench(smoke=False):
    """Autoregressive serving evidence pass (ISSUE 12 acceptance): Poisson
    arrivals of mixed-length greedy generation requests through
    GenerationEngine + GenerationScheduler (prefill/decode split, paged KV
    pool, token-level continuous batching), against a naive whole-sequence
    ablation server that re-runs the dense forward over the entire padded
    sequence for every generated token with one request in flight — the
    PR 6 single-shot serving answer to autoregression. Both paths are
    greedy off the same params, so the ablation is token-identical and the
    ratio isolates the serving strategy. Returns the GENSERVE.json record."""
    import threading

    from paddle_tpu.executor import aot_serve_lowering, scope_guard
    from paddle_tpu.models.gpt_decoder import GPTDecoder
    from paddle_tpu.observability import registry as _registry
    from paddle_tpu.serving import GenerationEngine, GenerationScheduler

    if smoke:
        model_kw = dict(vocab_size=64, n_layer=2, n_head=2, d_model=32,
                        d_inner=64, max_context=32)
        n_requests, max_slots, rate_req_s = 24, 4, 200.0
        naive_requests = 6
    else:
        model_kw = dict(vocab_size=256, n_layer=4, n_head=4, d_model=128,
                        d_inner=256, max_context=64)
        n_requests, max_slots, rate_req_s = 64, 8, 100.0
        naive_requests = 12
    name = "genbench"
    model = GPTDecoder(**model_kw)
    eng = GenerationEngine(model, name=name, max_slots=max_slots,
                           page_size=8, cache_dir=None)
    n_variants = eng.warmup()
    traces0 = eng.traces
    no_eos = model_kw["vocab_size"]  # out of range: every finish is "length"

    # shared-prefix workload: a couple of page-aligned "system prompts"
    # reused by 3/4 of the requests (prefix KV cache hits; the long shared
    # head also pushes those prompts past prefill_chunk, exercising chunked
    # prefill), plus a cold 1/4 sweeping the full prompt-length range
    rng = np.random.RandomState(0)
    ctx = eng.max_context
    ps = eng.page_size
    vocab = model_kw["vocab_size"]
    sys_len = min(4 * ps, (eng.max_prompt_len - 1) // ps * ps)
    sys_prompts = [
        [int(t) for t in rng.randint(0, vocab, size=sys_len)]
        for _ in range(2)
    ]
    reqs = []
    for i in range(n_requests):
        max_new = int(rng.randint(4, max(5, ctx // 2)))
        if i % 4 == 3:
            L = int(rng.randint(1, eng.max_prompt_len + 1))
            prompt = [int(t) for t in rng.randint(0, vocab, size=L)]
        else:
            tail = int(rng.randint(1, eng.max_prompt_len - sys_len + 1))
            prompt = sys_prompts[i % len(sys_prompts)] + [
                int(t) for t in rng.randint(0, vocab, size=tail)
            ]
        reqs.append((prompt, max_new))

    # ---- continuous batching under Poisson arrivals -----------------------
    sched = GenerationScheduler(eng, max_queue_requests=n_requests,
                                timeout_ms=120000.0)
    futures = []
    t0 = time.perf_counter()
    for prompt, max_new in reqs:
        futures.append(
            sched.submit(prompt, max_new_tokens=max_new, eos_id=no_eos)
        )
        time.sleep(rng.exponential(1.0 / rate_req_s))
    results = [f.result(300.0) for f in futures]
    wall = time.perf_counter() - t0
    sched.close(drain=True)
    cont_tokens = sum(len(r.tokens) for r in results)
    cont_tps = cont_tokens / wall
    traces_after = eng.traces - traces0

    reg = _registry.default_registry()
    ttft = reg.get("serving/%s/gen_ttft_ms" % name)
    tok = reg.get("serving/%s/gen_token_ms" % name)
    steps = reg.get("serving/%s/gen_steps" % name)
    n_steps = steps.value() if steps else 0

    # ---- naive whole-sequence ablation ------------------------------------
    # one dense forward over the full padded context per generated token,
    # requests strictly serial (prefix subset of the same workload, same
    # greedy math -> token parity is asserted, throughput is scaled per
    # token so the subset is fair)
    fwd_main, _, fwd_feeds, fwd_fetches = model.build_forward(1, ctx)
    with scope_guard(eng.scope):
        fwd, fwd_ro, _ = aot_serve_lowering(
            fwd_main, fwd_feeds, fwd_fetches, eng.scope
        )

    def naive_generate(prompt, max_new):
        toks = list(prompt)
        out = []
        budget = min(max_new, ctx - len(prompt))
        while len(out) < budget:
            buf = np.zeros((1, ctx, 1), np.int64)
            buf[0, :len(toks), 0] = toks
            (lg,) = fwd({"fwd_tokens": buf}, fwd_ro, {})
            nxt = int(np.asarray(lg)[0, len(toks) - 1].argmax())
            out.append(nxt)
            toks.append(nxt)
        return out

    naive_generate(*reqs[0])  # warm the jit before timing
    t0 = time.perf_counter()
    naive_out = [naive_generate(p, m) for p, m in reqs[:naive_requests]]
    naive_wall = time.perf_counter() - t0
    naive_tokens = sum(len(o) for o in naive_out)
    naive_tps = naive_tokens / naive_wall
    parity_ok = all(
        o == results[i].tokens for i, o in enumerate(naive_out)
    )

    # ---- head-of-line ablation (full mode): TTFT of short prompts that
    # arrive while a max-length prompt is streaming, chunked prefill vs
    # whole-prompt prefill (prefill_chunk = max_context) on identical
    # geometry — the number chunking exists to improve. Uses a 256-token
    # context so the whole-prompt prefill call is genuinely expensive
    # relative to one chunk; the first two rounds warm the host path and
    # are dropped.
    hol = None
    if not smoke:
        hol_kw = dict(model_kw, max_context=256)

        def _hol_short_ttft(chunk, tag):
            m2 = GPTDecoder(**hol_kw)
            e2 = GenerationEngine(m2, name="%s_%s" % (name, tag),
                                  max_slots=max_slots, page_size=8,
                                  prefill_chunk=chunk, prefix_cache=False,
                                  cache_dir=None)
            e2.warmup()
            s2 = GenerationScheduler(e2, max_queue_requests=64,
                                     timeout_ms=120000.0)
            long_p = [int(t) for t in
                      rng.randint(0, vocab, size=e2.max_prompt_len)]
            short_p = [int(t) for t in rng.randint(0, vocab, size=2)]
            lat = []
            for r in range(14):
                fl = s2.submit(long_p, max_new_tokens=8, eos_id=no_eos)
                for _ in range(3):
                    t0 = time.perf_counter()
                    s2.submit(short_p, max_new_tokens=1,
                              eos_id=no_eos).result(60.0)
                    if r >= 2:
                        lat.append((time.perf_counter() - t0) * 1e3)
                fl.result(60.0)
            s2.close(drain=True)
            lat.sort()
            return {
                "p50_ms": round(lat[len(lat) // 2], 3),
                "p99_ms": round(lat[min(len(lat) - 1,
                                        int(len(lat) * 0.99))], 3),
            }

        hol = {
            "long_prompt_tokens": hol_kw["max_context"] - 1,
            "chunked": _hol_short_ttft(None, "holc"),
            "whole_prompt": _hol_short_ttft(hol_kw["max_context"], "holw"),
        }

    pool = eng.pool.stats()
    est = eng.stats()
    pc = est.get("prefix_cache") or {}
    return {
        "metric": "generation_tokens_per_sec_per_chip",
        "value": round(cont_tps, 1),
        "unit": "tokens/sec",
        "requests": n_requests,
        "requests_ok": sum(1 for r in results if r.finish_reason),
        "served_fraction": round(len(results) / float(n_requests), 4),
        "tokens_generated": cont_tokens,
        "poisson_rate_req_s": rate_req_s,
        "mean_tokens_per_step": round(cont_tokens / n_steps, 2)
        if n_steps else None,
        "p50_ttft_ms": round(ttft.percentile(50), 3) if ttft else None,
        "p99_ttft_ms": round(ttft.percentile(99), 3) if ttft else None,
        "p50_token_ms": round(tok.percentile(50), 3) if tok else None,
        "p99_token_ms": round(tok.percentile(99), 3) if tok else None,
        "traces_after_warmup": traces_after,
        "variants": n_variants,
        "prefill_buckets": list(eng.prefill_buckets),
        "prefill_chunk": eng.prefill_chunk,
        "prefill_chunks": est["prefill_chunks"],
        "prefix_hit_rate": round(pc.get("hit_rate", 0.0), 4),
        "prefix_cache": pc,
        "kernel_dispatches": est["kernel_dispatches"],
        "hol_short_ttft_ms": hol,
        "geometry": eng.geometry(),
        "pool": pool,
        "naive_whole_sequence_tokens_per_sec": round(naive_tps, 1),
        "naive_ablation_requests": naive_requests,
        "naive_token_parity_ok": parity_ok,
        "continuous_vs_naive_x": round(cont_tps / naive_tps, 2),
        "model": {k: v for k, v in sorted(model_kw.items())},
        "max_slots": max_slots,
    }


class _ImgShardDecode:
    """Shard factory for the reader bench: deterministic synthetic uint8
    image batches with a real per-batch CPU decode cost (generate +
    augmentation passes) — the uncached path that cache_epoch cannot hide.
    Module-level and numpy-only so it runs inside data-runtime worker
    processes under fork or spawn."""

    def __init__(self, bs, hw, batches_per_shard, passes, classes=100,
                 seed=0):
        self.bs, self.hw = int(bs), int(hw)
        self.batches_per_shard = int(batches_per_shard)
        self.passes = int(passes)
        self.classes = int(classes)
        self.seed = int(seed)

    def __call__(self, shard_id, num_shards):
        rng = np.random.RandomState(self.seed * 100003 + shard_id)
        for _ in range(self.batches_per_shard):
            raw = rng.randint(
                0, 256, (self.bs, 3, self.hw, self.hw)
            ).astype(np.uint8)
            img = raw.astype(np.float32)
            for _ in range(self.passes):  # flip/jitter/clip: decode cost
                img = img[:, :, ::-1, :] * 1.01 + 0.5
                np.clip(img, 0.0, 255.0, out=img)
            yield {
                "img": img.astype(np.uint8),  # compact wire: bytes over PCIe
                "label": rng.randint(
                    0, self.classes, (self.bs, 1)
                ).astype(np.int64),
            }


class _TokShardDecode:
    """Shard factory for the token path: int64 id batches with a
    tokenizer-like CPU cost (sort/cumsum passes over the ids)."""

    def __init__(self, bs, tlen, batches_per_shard, passes, vocab, seed=0):
        self.bs, self.tlen = int(bs), int(tlen)
        self.batches_per_shard = int(batches_per_shard)
        self.passes = int(passes)
        self.vocab = int(vocab)
        self.seed = int(seed)

    def __call__(self, shard_id, num_shards):
        rng = np.random.RandomState(self.seed * 100003 + shard_id)
        for _ in range(self.batches_per_shard):
            toks = rng.randint(
                1, self.vocab, (self.bs, self.tlen, 1)
            ).astype(np.int64)
            for _ in range(self.passes):
                np.cumsum(np.sort(toks, axis=1), axis=1)
            yield {
                "words": toks,
                "label": rng.randint(0, 2, (self.bs, 1)).astype(np.int64),
            }


def _reader_feed_pass(exe, main, loss, factory, feed_names, num_shards,
                      num_workers):
    """One warm epoch (worker spin-up + XLA compile), then a timed epoch.
    Returns (batches, wall_s, stall_s): stall is the time next_batch spent
    BLOCKED waiting for data — the end-to-end feed-stall the PR 4 StepStats
    hook measures on the same call path — so frac = stall/wall is the
    fraction of the epoch the device would have idled on input."""
    from paddle_tpu.py_reader import EOFException, PyReader

    reader = PyReader(feed_names, capacity=4)
    if num_workers > 0:
        reader.decorate_tensor_provider(
            factory, num_workers=num_workers, num_shards=num_shards
        )
    else:
        def seq():  # identical decode work, single in-process feeder thread
            for s in range(num_shards):
                for feed in factory(s, num_shards):
                    yield feed

        reader.decorate_tensor_provider(seq, num_workers=0)
    l = None
    try:
        reader.start()
        for feed in reader():
            (l,) = exe.run(main, feed=feed, fetch_list=[loss.name],
                           return_numpy=False)
        np.asarray(l)
        reader.start()
        batches, stall = 0, 0.0
        t0 = time.perf_counter()
        while True:
            tf = time.perf_counter()
            try:
                feed = reader.next_batch()
            except EOFException:
                break
            stall += time.perf_counter() - tf
            (l,) = exe.run(main, feed=feed, fetch_list=[loss.name],
                           return_numpy=False)
            batches += 1
        np.asarray(l)  # sync before stopping the clock
        wall = time.perf_counter() - t0
    finally:
        reader.close()
    return batches, wall, stall


def _staged_ceiling(exe, main, loss, feed, steps):
    """Device-prestaged throughput: the compute ceiling the feed passes are
    measured against (batches/sec)."""
    import jax

    dev = {k: jax.device_put(v) for k, v in feed.items()}
    for _ in range(2):
        (l,) = exe.run(main, feed=dev, fetch_list=[loss.name],
                       return_numpy=False)
    np.asarray(l)
    t0 = time.perf_counter()
    for _ in range(steps):
        (l,) = exe.run(main, feed=dev, fetch_list=[loss.name],
                       return_numpy=False)
    np.asarray(l)
    return steps / (time.perf_counter() - t0)


def run_reader_bench(smoke=False, num_workers=None):
    """ISSUE 7 evidence pass → BENCH_reader.json: the uncached uint8-image
    and token feed paths, each measured three ways — device-prestaged
    ceiling, single-threaded PyReader (num_workers=0, the pre-runtime hot
    path), and the native data runtime (multiprocess decode + shm ring +
    async device staging, docs/data.md). `pyreader_frac` here is the
    FEED-STALL FRACTION of epoch wall time (time next_batch blocked on
    input / total), the acceptance metric: < 0.05 with the runtime on the
    bench chip, < 0.2 in the CPU CI smoke."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu import framework
    from paddle_tpu.executor import Scope, scope_guard

    if smoke:
        nw = int(num_workers or 2)
        img_cfg = dict(bs=16, hw=32, batches_per_shard=6, passes=4)
        tok_cfg = dict(bs=16, tlen=64, batches_per_shard=6, passes=4,
                       vocab=1024)
        shards = 8
    else:
        nw = int(num_workers or 4)
        img_cfg = dict(bs=64, hw=96, batches_per_shard=4, passes=8)
        tok_cfg = dict(bs=64, tlen=256, batches_per_shard=4, passes=24,
                       vocab=8192)
        shards = 16

    hw, tlen, vocab = img_cfg["hw"], tok_cfg["tlen"], tok_cfg["vocab"]

    def build_image():
        main_p, startup = framework.Program(), framework.Program()
        with fluid.unique_name.guard(), fluid.program_guard(main_p, startup):
            img = fluid.layers.data(name="img", shape=[3, hw, hw],
                                    dtype="float32")
            label = fluid.layers.data(name="label", shape=[1], dtype="int64")
            h = fluid.layers.conv2d(img, num_filters=16, filter_size=3,
                                    stride=2, act="relu")
            h = fluid.layers.conv2d(h, num_filters=32, filter_size=3,
                                    stride=2, act="relu")
            h = fluid.layers.pool2d(h, pool_size=2, pool_stride=2)
            logits = fluid.layers.fc(h, size=100)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, label)
            )
            fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
        return main_p, startup, loss

    def build_tokens():
        main_p, startup = framework.Program(), framework.Program()
        with fluid.unique_name.guard(), fluid.program_guard(main_p, startup):
            words = fluid.layers.data(name="words", shape=[tlen, 1],
                                      dtype="int64")
            label = fluid.layers.data(name="label", shape=[1], dtype="int64")
            emb = fluid.layers.embedding(words, size=[vocab, 128])
            h = fluid.layers.reduce_mean(emb, dim=1)
            h = fluid.layers.fc(h, size=256, act="relu")
            logits = fluid.layers.fc(h, size=2)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, label)
            )
            fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
        return main_p, startup, loss

    exe = fluid.Executor(fluid.TPUPlace())
    record = {"metric": "reader_pipeline", "mode": "smoke" if smoke else
              "full", "num_workers": nw, "num_shards": shards}
    for key, build, dec, unit in (
        ("image", build_image, _ImgShardDecode(**img_cfg), img_cfg["bs"]),
        ("tokens", build_tokens, _TokShardDecode(**tok_cfg),
         tok_cfg["bs"] * tok_cfg["tlen"]),
    ):
        main_p, startup, loss = build()
        with scope_guard(Scope(seed=0)):  # fresh scope: no param collisions
            exe.run(startup)
            probe = next(dec(0, shards))
            ceiling = _staged_ceiling(exe, main_p, loss, probe,
                                      steps=shards * 3) * unit
            b0, w0, s0 = _reader_feed_pass(
                exe, main_p, loss, dec,
                list(probe), shards, num_workers=0,
            )
            b1, w1, s1 = _reader_feed_pass(
                exe, main_p, loss, dec,
                list(probe), shards, num_workers=nw,
            )
            thread_ips, rt_ips = b0 * unit / w0, b1 * unit / w1
            if key == "image":
                path = {
                    "staged_images_per_sec": round(ceiling, 2),
                    "pyreader_images_per_sec": round(thread_ips, 2),
                    "pyreader_frac": round(s0 / w0, 3),
                    "pyreader_images_per_sec_runtime": round(rt_ips, 2),
                    "pyreader_frac_runtime": round(s1 / w1, 3),
                }
            else:
                path = {
                    "staged_tokens_per_sec": round(ceiling, 1),
                    "tokens_per_sec": round(thread_ips, 1),
                    "pyreader_frac_tokens": round(s0 / w0, 3),
                    "tokens_per_sec_runtime": round(rt_ips, 1),
                    "pyreader_frac_tokens_runtime": round(s1 / w1, 3),
                }
            path["runtime_speedup_x"] = round(rt_ips / thread_ips, 2)
            path["batches_per_epoch"] = b1
            record[key] = path
    return record


def _recsys_build(rows, fields, dim, is_sparse, use_distributed,
                  optimizer="adam", layer_sizes=(32, 16)):
    import paddle_tpu.fluid as fluid
    from paddle_tpu import framework
    from paddle_tpu.models.deepfm import deepfm

    main_p, startup = framework.Program(), framework.Program()
    with fluid.unique_name.guard(), fluid.program_guard(main_p, startup):
        ids = fluid.layers.data(name="ids", shape=[fields, 1], dtype="int64")
        label = fluid.layers.data(name="label", shape=[1], dtype="float32")
        loss, pred, _ = deepfm(
            ids, label, num_features=rows, num_fields=fields,
            embedding_size=dim, layer_sizes=layer_sizes,
            is_sparse=is_sparse, use_distributed=use_distributed,
        )
        if optimizer == "adam":
            # bf16-stored moments: the TPU-native state precision; per-row
            # sparse updates gather/cast/scatter them alongside the table
            fluid.optimizer.Adam(
                learning_rate=1e-3, moment_dtype="bfloat16"
            ).minimize(loss)
        else:
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main_p, startup, loss


def _recsys_batches(rng, rows, fields, batch, n):
    out = []
    for _ in range(n):
        ids = rng.randint(0, rows, (batch, fields, 1)).astype("int64")
        label = (rng.rand(batch, 1) < 0.5).astype("float32")
        out.append({"ids": ids, "label": label})
    return out


def _recsys_time(run_step, batches, warmup=2, windows=2, steps=6):
    """min-over-windows ms/step (harness noise only ever adds time)."""
    for i in range(warmup):
        l = run_step(batches[i % len(batches)])
    np.asarray(l)
    best = float("inf")
    for _ in range(windows):
        t0 = time.perf_counter()
        for i in range(steps):
            l = run_step(batches[i % len(batches)])
        np.asarray(l)
        best = min(best, (time.perf_counter() - t0) / steps)
    return best * 1e3


def run_recsys_bench(smoke=False):
    """Sparse embedding engine evidence pass (PR 8) → BENCH_recsys.json.

    Three legs over the DeepFM CTR model (models/deepfm.py, two shared-id
    tables fm_first[rows,1] + fm_emb[rows,dim]):

    1. update-cost: dense Adam (full-table moment decay each step) vs
       is_sparse=True (SelectedRows grads + per-row lazy-Adam updates) on one
       device at <=1% rows touched per step — the sparse step must be
       measurably faster since its optimizer cost is O(touched rows);
    2. ep-sharded throughput: the same sparse model row-sharded over every
       local device via ParallelExecutor + MeshConfig(ep=n) — headline
       `embedding_rows_per_sec` (table rows gathered+updated per second,
       batch*fields*2 tables per step);
    3. parity: sparse ep-sharded SGD vs dense single-device SGD on identical
       batches (the engine changes data layout, not math — SGD is
       bit-exact; see tests/test_deepfm.py for the assertion-grade version).

    Size accounting rides along: table + dense f32 Adam state vs the
    per-chip share when row-sharded with bf16 moments (the "giant table"
    claim — the table's dense state does not fit one chip's fair share)."""
    import jax
    import paddle_tpu.fluid as fluid
    from paddle_tpu.executor import Scope, scope_guard
    from paddle_tpu.parallel import MeshConfig

    n_dev = jax.device_count()
    if smoke:
        rows, dim, fields, batch = 4096, 8, 6, 128
        steps, layer_sizes = 4, (16,)
    else:
        rows, dim, fields, batch = 1 << 20, 32, 16, 512
        steps, layer_sizes = 6, (32, 16)
    if rows % max(n_dev, 1):
        rows -= rows % n_dev  # row-sharding needs divisibility
    rng = np.random.RandomState(0)
    batches = _recsys_batches(rng, rows, fields, batch, 4)
    record = {
        "metric": "recsys_deepfm",
        "mode": "smoke" if smoke else "full",
        "table_rows": rows,
        "embedding_dim": dim,
        "num_fields": fields,
        "batch_size": batch,
        "devices": n_dev,
        "rows_touched_frac": round(batch * fields / float(rows), 5),
    }

    exe = fluid.Executor(fluid.TPUPlace())

    # ---- leg 1: dense vs sparse update cost, single device ----------------
    for key, sparse in (("dense", False), ("sparse", True)):
        main_p, startup, loss = _recsys_build(
            rows, fields, dim, is_sparse=sparse, use_distributed=False,
            layer_sizes=layer_sizes,
        )
        with scope_guard(Scope(seed=0)):
            exe.run(startup)
            ms = _recsys_time(
                lambda feed: exe.run(main_p, feed=feed,
                                     fetch_list=[loss.name],
                                     return_numpy=False)[0],
                batches, steps=steps,
            )
        record["%s_step_ms_1dev" % key] = round(ms, 2)
    record["sparse_vs_dense_update_speedup_x"] = round(
        record["dense_step_ms_1dev"] / record["sparse_step_ms_1dev"], 2
    )

    # ---- leg 2: ep-sharded sparse throughput ------------------------------
    sharded_ms = None
    if n_dev > 1:
        main_p, startup, loss = _recsys_build(
            rows, fields, dim, is_sparse=True, use_distributed=True,
            layer_sizes=layer_sizes,
        )
        with scope_guard(Scope(seed=0)):
            exe.run(startup)
            pe = fluid.ParallelExecutor(
                use_cuda=False, loss_name=loss.name, main_program=main_p,
                mesh_config=MeshConfig(dp=1, ep=n_dev),
            )
            sharded_ms = _recsys_time(
                lambda feed: pe.run([loss.name], feed=feed,
                                    return_numpy=False)[0],
                batches, steps=steps,
            )
        record["sharded_step_ms_ep%d" % n_dev] = round(sharded_ms, 2)
    rows_per_step = batch * fields * 2  # both tables gather+update per id
    best_ms = min(
        m for m in (sharded_ms, record["sparse_step_ms_1dev"]) if m
    )
    record["embedding_rows_per_sec"] = round(rows_per_step / best_ms * 1e3, 1)

    # ---- size accounting: the giant-table claim ---------------------------
    fbytes = rows * dim * 4
    table_bytes = fbytes + rows * 1 * 4  # fm_emb + fm_first
    dense_state = 2 * (fbytes + rows * 4)  # two f32 moment sets, both tables
    sharded_per_chip = (table_bytes + (fbytes + rows * 4)) // max(n_dev, 1)
    # table f32 + 2x bf16 moments, row-sharded over the mesh
    record["table_bytes"] = table_bytes
    record["dense_opt_state_bytes"] = dense_state
    record["sharded_table_plus_state_bytes_per_chip"] = sharded_per_chip
    record["table_over_chip_state_share_x"] = round(
        (table_bytes + dense_state) / float(sharded_per_chip), 2
    )

    # ---- leg 3: sparse ep-sharded vs dense 1-dev loss parity (SGD) --------
    prows, pfields, pdim, pbatch = 2048, 4, 8, 64
    if prows % max(n_dev, 1):
        prows -= prows % n_dev
    prng = np.random.RandomState(7)
    pbatches = _recsys_batches(prng, prows, pfields, pbatch, 6)

    def parity_losses(distributed):
        main_p, startup, loss = _recsys_build(
            prows, pfields, pdim, is_sparse=distributed,
            use_distributed=distributed, optimizer="sgd", layer_sizes=(16,),
        )
        losses = []
        with scope_guard(Scope(seed=3)):
            exe.run(startup)
            if distributed and n_dev > 1:
                pe = fluid.ParallelExecutor(
                    use_cuda=False, loss_name=loss.name, main_program=main_p,
                    mesh_config=MeshConfig(dp=1, ep=n_dev),
                )
                step = lambda feed: pe.run([loss.name], feed=feed)[0]
            else:
                step = lambda feed: exe.run(
                    main_p, feed=feed, fetch_list=[loss.name]
                )[0]
            for feed in pbatches:
                losses.append(float(np.asarray(step(feed)).reshape(-1)[0]))
        return losses

    dense_l = parity_losses(False)
    sparse_l = parity_losses(True)
    diff = max(abs(a - b) for a, b in zip(dense_l, sparse_l))
    record["parity_max_loss_diff"] = round(diff, 6)
    record["parity_steps"] = len(pbatches)
    return record


def _passes_build_lenet():
    import paddle_tpu.fluid as fluid
    from paddle_tpu import framework
    from paddle_tpu.models import lenet5

    main = framework.Program()
    startup = framework.Program()
    with fluid.unique_name.guard():
        with fluid.program_guard(main, startup):
            img = fluid.layers.data(name="img", shape=[1, 28, 28],
                                    dtype="float32")
            label = fluid.layers.data(name="label", shape=[1], dtype="int64")
            out = lenet5(img, label)
            loss = out[0] if isinstance(out, tuple) else out
            fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    return main, startup, loss


def _passes_build_transformer():
    import paddle_tpu.fluid as fluid
    from paddle_tpu import framework
    from paddle_tpu.models.transformer import build_tiny_flash_transformer

    main = framework.Program()
    startup = framework.Program()
    with fluid.unique_name.guard():
        with fluid.program_guard(main, startup):
            _feeds, loss = build_tiny_flash_transformer()
            fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    return main, startup, loss


def _passes_feed(model, rng, batch):
    if model == "lenet":
        return {
            "img": rng.randn(batch, 1, 28, 28).astype("float32"),
            "label": rng.randint(0, 10, (batch, 1)).astype("int64"),
        }
    from paddle_tpu.models.transformer import tiny_flash_transformer_feed

    return tiny_flash_transformer_feed(batch, seed=int(rng.randint(1 << 30)))


def run_passes_bench(smoke=False):
    """Pass-framework evidence (ISSUE 10 -> PASSES.json): for LeNet and the
    tiny flash transformer, pipeline off vs the training_default preset —
    steady-state step time, program op count before/after, compiled HLO
    instruction count, per-pass payloads (folded/removed/fusion groups), and
    the max loss delta over lockstep training (must be < 1e-6: the pipeline
    preserves the RNG stream, so training is bit-identical)."""
    from paddle_tpu import flags, passes
    from paddle_tpu.executor import Executor, Scope, scope_guard

    steps = 4 if smoke else 10
    warmup = 2
    record = {"metric": "graph_passes", "mode": "smoke" if smoke else "full",
              "preset": "training_default",
              "pipeline": list(passes.PRESETS["training_default"]),
              "models": {}}

    for model, builder, batch in (
        ("lenet", _passes_build_lenet, 32),
        ("transformer", _passes_build_transformer, 8),
    ):
        entry = {}
        losses = {}
        for pipeline in ("off", "training_default"):
            flags.set_flags({"pass_pipeline":
                             "" if pipeline == "off" else pipeline})
            try:
                main_p, startup, loss = builder()
                exe = Executor()
                rng = np.random.RandomState(0)
                with scope_guard(Scope(seed=7)):
                    from paddle_tpu.executor import global_scope

                    exe.run(startup)
                    ls = []
                    feed_names = None
                    for _ in range(warmup):
                        feed = _passes_feed(model, rng, batch)
                        feed_names = sorted(feed)
                        ls.append(float(np.asarray(exe.run(
                            main_p, feed=feed, fetch_list=[loss.name],
                        )[0]).reshape(-1)[0]))
                    t0 = time.perf_counter()
                    for _ in range(steps):
                        ls.append(float(np.asarray(exe.run(
                            main_p, feed=_passes_feed(model, rng, batch),
                            fetch_list=[loss.name],
                        )[0]).reshape(-1)[0]))
                    step_ms = (time.perf_counter() - t0) / steps * 1e3
                    hlo = exe.compiled_hlo()
                    if pipeline != "off":
                        # the memoized transformed program the executor just
                        # ran (same key: program, pipeline, scope, feed/fetch
                        # -> cache hit, not a re-application)
                        transformed = passes.apply_cached(
                            main_p, pipeline, scope=global_scope(),
                            feed_names=feed_names,
                            fetch_names=[loss.name],
                        )
                        entry["ops_after"] = sum(
                            len(b.ops) for b in transformed.blocks
                        )
                        results = transformed._pass_results
                        entry["folded"] = results.get(
                            "constant_fold", {}).get("folded", 0)
                        entry["dce_removed"] = results.get(
                            "dead_op_eliminate", {}).get("removed", 0)
                        entry["fusion_groups"] = results.get(
                            "fuse_elemwise_act", {}).get("groups", 0)
                losses[pipeline] = ls
                key = "off" if pipeline == "off" else "on"
                entry["step_ms_%s" % key] = round(step_ms, 3)
                entry["hlo_instructions_%s" % key] = hlo.count(" = ")
                if pipeline == "off":
                    entry["ops_before"] = sum(
                        len(b.ops) for b in main_p.blocks
                    )
            finally:
                flags.set_flags({"pass_pipeline": ""})
        entry["op_reduction"] = entry["ops_before"] - entry["ops_after"]
        entry["max_loss_delta"] = max(
            abs(a - b)
            for a, b in zip(losses["off"], losses["training_default"])
        )
        record["models"][model] = entry
    record["parity_ok"] = all(
        m["max_loss_delta"] < 1e-6 for m in record["models"].values()
    )
    return record


# v5e chip conventions for the quant bench's roofline projections (the
# int8/fp8 MXU rate claims cannot be measured on the CPU CI host: XLA-CPU
# lowers the int8 dot through a slow emulation path, so the CPU-measured
# int8/native ratio measures that emulation, not the chip — both numbers
# ride the record, clearly labeled)
V5E_INT8_TOPS = 2.0 * NOMINAL_BF16_TFLOPS  # MXU int8/fp8 rate is 2x bf16
V5E_HBM_GBS = 819.0


def _quant_fit_classifier(model_dir, build_net, feed_shape, feed_dtype,
                          batch_fn, steps, bs, seed=11):
    """Fit a zoo classifier on synthetic clustered batches (Adam) and
    save_inference_model(model_dir). The int8 accuracy gate needs an fp32
    oracle with real decision margins: a random-init deep net's top-1 sits
    at ~zero logit margin, so int8-vs-fp32 'disagreement' there measures
    logit degeneracy, not quantization fidelity. Returns the fp32 training
    loss curve endpoints (first, last) as a fit sanity check."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu import framework
    from paddle_tpu.executor import Scope, scope_guard

    main, startup = framework.Program(), framework.Program()
    with fluid.unique_name.guard(), fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=feed_shape,
                                dtype=feed_dtype)
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        loss, logits = build_net(img, label)
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    exe = fluid.Executor()
    rng = np.random.RandomState(seed)
    first = last = None
    with scope_guard(Scope(seed=seed)):
        exe.run(startup)
        for i in range(steps):
            x, y = batch_fn(rng, bs)
            (lv,) = exe.run(main, feed={"img": x, "label": y},
                            fetch_list=[loss.name])
            last = float(np.asarray(lv).reshape(()))
            if first is None:
                first = last
        fluid.io.save_inference_model(
            model_dir, ["img"], [logits], exe, main_program=main
        )
    return first, last


def _quant_eval_classifier(model_dir, name, batch_fn, calib_batches,
                           eval_batches, eval_bs, seed=3):
    """fp32-vs-int8 evidence for one saved classifier: top-1 accuracy of
    each engine against the synthetic labels, per-example agreement, logit
    drift, and the CPU rows/s of both engines."""
    from paddle_tpu.serving import ServingEngine

    rng = np.random.RandomState(seed)
    calib = [{"img": batch_fn(rng, 16)[0]} for _ in range(calib_batches)]
    e_f32 = ServingEngine(model_dir, name=name + "_f32", cache_dir=None)
    e_i8 = ServingEngine(model_dir, name=name + "_i8", cache_dir=None,
                         precision="int8", calibration_feeds=calib)
    ok32 = ok8 = agree = tot = 0
    drift = 0.0
    t32 = t8 = 0.0
    for _ in range(eval_batches):
        x, y = batch_fn(rng, eval_bs)
        t0 = time.perf_counter()
        (a,) = e_f32.run({"img": x})
        t32 += time.perf_counter() - t0
        t0 = time.perf_counter()
        (b,) = e_i8.run({"img": x})
        t8 += time.perf_counter() - t0
        pa, pb = np.argmax(a, -1), np.argmax(b, -1)
        yy = y.reshape(-1)
        ok32 += int((pa == yy).sum())
        ok8 += int((pb == yy).sum())
        agree += int((pa == pb).sum())
        tot += x.shape[0]
        drift = max(drift, float(
            np.abs(a - b).max() / (np.abs(a).max() + 1e-9)
        ))
    q = e_i8.stats()["quant"]
    return {
        "top1_fp32": round(ok32 / tot, 4),
        "top1_int8": round(ok8 / tot, 4),
        "top1_delta": round(abs(ok32 - ok8) / tot, 4),
        "agreement": round(agree / tot, 4),
        "eval_examples": tot,
        "max_rel_logit_err": round(drift, 4),
        "quantized_muls": q["quantized_muls"],
        "calibrated_ranges": q["calibrated_ranges"],
        "fused_groups": q["fused_groups"],
        "cpu_fp32_rows_per_sec": round(tot / t32, 1),
        "cpu_int8_rows_per_sec": round(tot / t8, 1),
    }, e_i8


def _quant_v5e_roofline(mm_flops, w_elems, act_elems):
    """Single-shot serving time on one v5e, bf16 weights vs the calibrated
    int8 path, under the MXU-rate/HBM-bandwidth roofline. bf16 reads
    2B/elem weights+activations; int8 reads 1B/elem weights but pays the
    quantize_static activation pass (4B f32 read + 1B write + 1B GEMM
    re-read). Epilogue dequant is folded into the kernel (free)."""
    bw = V5E_HBM_GBS * 1e9
    t_bf16 = max(mm_flops / (NOMINAL_BF16_TFLOPS * 1e12),
                 (2.0 * w_elems + 2.0 * act_elems) / bw)
    t_int8 = max(mm_flops / (V5E_INT8_TOPS * 1e12),
                 (1.0 * w_elems + 6.0 * act_elems) / bw)
    return {
        "mm_gflops": round(mm_flops / 1e9, 2),
        "weight_melems": round(w_elems / 1e6, 2),
        "act_melems": round(act_elems / 1e6, 2),
        "peak_bf16_tflops": NOMINAL_BF16_TFLOPS,
        "peak_int8_tops": V5E_INT8_TOPS,
        "hbm_gbs": V5E_HBM_GBS,
        "t_bf16_us": round(t_bf16 * 1e6, 2),
        "t_int8_us": round(t_int8 * 1e6, 2),
        "speedup_x": round(t_bf16 / t_int8, 2),
    }


def run_quant_bench(smoke=False):
    """Quantization evidence pass (ISSUE 18 acceptance) -> QUANT.json.

    Four sections: (1) zoo classifiers briefly fit on synthetic clusters,
    fp32 oracle vs calibrated-int8 ServingEngine top-1 (the <0.5% accuracy
    gate); (2) the fc-stack serving head — the matmul-dominated honest
    vehicle for the int8 MXU rate, same reasoning as run_transformer_mfu —
    with the v5e roofline projection carrying the chip-rate claim and the
    CPU-measured ratio riding alongside; (3) the kv-int8 GenerationEngine
    at 2x max_slots in fewer pool bytes, with greedy-token agreement and
    the last-step logit-drift bound; (4) the FLAGS_fp8_matmul training
    step-time entry alongside BENCH_r06's bf16 number."""
    import shutil
    import tempfile

    import paddle_tpu.fluid as fluid
    from paddle_tpu.executor import Scope
    from paddle_tpu.models.gpt_decoder import GPTDecoder
    from paddle_tpu.models.lenet import lenet5
    from paddle_tpu.serving import GenerationEngine, GenerationScheduler

    record = {"metric": "quant_serving", "smoke": bool(smoke)}
    tmp = tempfile.mkdtemp(prefix="quant-bench-")
    try:
        # ---- (1) zoo classifiers: int8 top-1 within 0.5% of fp32 ----------
        fit_steps, eval_batches, eval_bs = (
            (10, 2, 128) if smoke else (30, 8, 250)
        )

        lenet_means = np.random.RandomState(100).rand(10, 1, 28, 28)

        def lenet_batch(rng, bs):
            y = rng.randint(0, 10, (bs, 1)).astype("int64")
            x = (0.7 * lenet_means[y.reshape(-1)]
                 + 0.3 * rng.rand(bs, 1, 28, 28)).astype("float32")
            return x, y

        def lenet_net(img, label):
            loss, _acc, logits = lenet5(img, label)
            return loss, logits

        d1 = os.path.join(tmp, "lenet")
        l0, l1 = _quant_fit_classifier(
            d1, lenet_net, [1, 28, 28], "float32", lenet_batch,
            steps=fit_steps, bs=64,
        )
        zoo_lenet, _ = _quant_eval_classifier(
            d1, "q_lenet", lenet_batch, calib_batches=8,
            eval_batches=eval_batches, eval_bs=eval_bs,
        )
        zoo_lenet["fit_loss_first_last"] = [round(l0, 3), round(l1, 3)]

        # fc-stack classifier head (the deep&wide serving shape: every mul
        # quantizes, so this model also vehicles the throughput section)
        d_model, classes, depth = (256, 16, 2) if smoke else (2048, 16, 3)
        head_means = np.random.RandomState(101).randn(classes, d_model)

        def head_batch(rng, bs):
            y = rng.randint(0, classes, (bs, 1)).astype("int64")
            x = (head_means[y.reshape(-1)]
                 + 0.7 * rng.randn(bs, d_model)).astype("float32")
            return x, y

        def head_net(img, label):
            h = img
            for _ in range(depth):
                h = fluid.layers.fc(h, size=d_model, act="relu")
            logits = fluid.layers.fc(h, size=classes)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, label)
            )
            return loss, logits

        d2 = os.path.join(tmp, "fc_head")
        h0, h1 = _quant_fit_classifier(
            d2, head_net, [d_model], "float32", head_batch,
            steps=fit_steps, bs=64,
        )
        zoo_head, e_head_i8 = _quant_eval_classifier(
            d2, "q_head", head_batch, calib_batches=8,
            eval_batches=eval_batches, eval_bs=eval_bs,
        )
        zoo_head["fit_loss_first_last"] = [round(h0, 3), round(h1, 3)]
        record["zoo"] = {"lenet5": zoo_lenet, "fc_head": zoo_head}
        record["top1_delta_max"] = max(
            zoo_lenet["top1_delta"], zoo_head["top1_delta"]
        )

        # ---- (2) single-shot throughput: v5e roofline + CPU measured ------
        # op mix counted from what quantize_serving actually froze
        B = 128 if smoke else 1024
        scope = e_head_i8.scope
        frozen = e_head_i8.quant_results["quantize_serving"]["weights_frozen"]
        mm_flops = w_elems = act_elems = 0
        for wname in frozen:
            k, n = np.asarray(scope.find_var(wname)).shape
            mm_flops += 2.0 * B * k * n
            w_elems += k * n
            act_elems += B * k
        roof = _quant_v5e_roofline(mm_flops, w_elems, act_elems)
        record["single_shot"] = {
            "model": {"d_model": d_model, "depth": depth, "classes": classes},
            "batch_rows": B,
            "v5e_roofline": roof,
            "int8_vs_bf16_x_v5e": roof["speedup_x"],
            # CPU ratio measures XLA-CPU's int8-dot emulation, not the MXU
            "cpu_measured_x": round(
                zoo_head["cpu_int8_rows_per_sec"]
                / zoo_head["cpu_fp32_rows_per_sec"], 3,
            ),
        }

        # ---- (3) kv-int8 generation: 2x slots in fewer pool bytes ---------
        if smoke:
            kv_kw = dict(vocab_size=64, n_layer=2, n_head=2, d_model=32,
                         d_inner=64, max_context=32)
            base_slots, n_parity, n_sched = 4, 3, 8
        else:
            kv_kw = dict(vocab_size=256, n_layer=4, n_head=4, d_model=128,
                         d_inner=256, max_context=64)
            base_slots, n_parity, n_sched = 8, 8, 32
        no_eos = kv_kw["vocab_size"]
        e_f32 = GenerationEngine(
            GPTDecoder(**kv_kw), name="qkv_f32", max_slots=base_slots,
            page_size=8, cache_dir=None, scope=Scope(seed=11),
        )
        e_i8 = GenerationEngine(
            GPTDecoder(kv_dtype="int8", **kv_kw), name="qkv_i8",
            max_slots=2 * base_slots, page_size=8, cache_dir=None,
            scope=Scope(seed=11),
        )
        rng = np.random.RandomState(0)
        vocab = kv_kw["vocab_size"]
        drift = 0.0
        tok_same = tok_all = 0
        for _ in range(n_parity):
            L = int(rng.randint(4, e_f32.max_prompt_len - 8))
            p = [int(t) for t in rng.randint(0, vocab, size=L)]
            r32 = e_f32.generate(p, max_new_tokens=8, eos_id=no_eos)
            l32 = e_f32.last_logits[0].copy()
            ri8 = e_i8.generate(p, max_new_tokens=8, eos_id=no_eos)
            li8 = e_i8.last_logits[0].copy()
            tok_same += sum(a == b for a, b in zip(r32.tokens, ri8.tokens))
            tok_all += len(r32.tokens)
            drift = max(drift, float(
                np.abs(l32 - li8).max() / (np.abs(l32).max() + 1e-9)
            ))

        # GENSERVE-style continuous-batching load on the int8-kv engine
        sched = GenerationScheduler(e_i8, max_queue_requests=n_sched,
                                    timeout_ms=120000.0)
        futures = []
        t0 = time.perf_counter()
        for _ in range(n_sched):
            L = int(rng.randint(1, e_i8.max_prompt_len + 1))
            p = [int(t) for t in rng.randint(0, vocab, size=L)]
            mx = int(rng.randint(4, max(5, e_i8.max_context // 2)))
            futures.append(sched.submit(p, max_new_tokens=mx, eos_id=no_eos))
            time.sleep(rng.exponential(1.0 / 100.0))
        results = [f.result(300.0) for f in futures]
        wall = time.perf_counter() - t0
        sched.close(drain=True)
        toks = sum(len(r.tokens) for r in results)

        p32, p8 = e_f32.pool.stats(), e_i8.pool.stats()
        record["kv_int8"] = {
            "baseline_max_slots": base_slots,
            "max_slots": 2 * base_slots,
            "max_slots_x": 2.0,
            "pool_bytes_f32": p32["resident_bytes"],
            "pool_bytes_int8_2x_slots": p8["resident_bytes"],
            "pool_bytes_x": round(
                p8["resident_bytes"] / p32["resident_bytes"], 3
            ),
            "storage_dtype": p8["storage_dtype"],
            "token_agreement": round(tok_same / tok_all, 4),
            "max_rel_logit_drift": round(drift, 4),
            "tokens_per_sec": round(toks / wall, 1),
            "requests": n_sched,
            "requests_ok": sum(1 for r in results if r.finish_reason),
            "geometry": e_i8.geometry(),
            "model": {k: v for k, v in sorted(kv_kw.items())},
        }

        # ---- (4) fp8 training-matmul step time ----------------------------
        from paddle_tpu import flags as _flags
        from paddle_tpu.executor import scope_guard
        from paddle_tpu.ops.pallas_kernels import KERNEL_DISPATCHES

        t_kw = (dict(b=2, t=32, d=64, n_layer=1, vocab=256) if smoke
                else dict(b=2, t=64, d=128, n_layer=2, vocab=512))
        t_steps = 3 if smoke else 6

        def fp8_step(fp8_on):
            _flags.set_flags({"fp8_matmul": bool(fp8_on)})
            try:
                main, startup, feed, loss, flops = build_transformer(**t_kw)
                exe = fluid.Executor()
                with scope_guard(Scope(seed=0)):
                    exe.run(startup)
                    before = KERNEL_DISPATCHES.get("matmul_fp8", 0)
                    for _ in range(2):
                        (lv,) = exe.run(main, feed=feed,
                                        fetch_list=[loss.name],
                                        return_numpy=False)
                    np.asarray(lv)
                    t0 = time.perf_counter()
                    for _ in range(t_steps):
                        (lv,) = exe.run(main, feed=feed,
                                        fetch_list=[loss.name],
                                        return_numpy=False)
                    lf = float(np.asarray(lv).reshape(()))
                    dt = (time.perf_counter() - t0) / t_steps
                return dt, lf, KERNEL_DISPATCHES.get("matmul_fp8", 0) - before
            finally:
                _flags.set_flags({"fp8_matmul": False})

        dt_base, loss_base, _ = fp8_step(False)
        dt_fp8, loss_fp8, n_disp = fp8_step(True)
        r06_bf16 = None
        try:
            with open(os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    "BENCH_r06.json")) as f:
                r06_bf16 = json.load(f)["parsed"].get(
                    "transformer_tflops_per_sec")
        except Exception:
            pass
        record["fp8_transformer"] = {
            "model": t_kw,
            "cpu_step_ms_baseline": round(dt_base * 1e3, 2),
            "cpu_step_ms_fp8": round(dt_fp8 * 1e3, 2),
            "matmul_fp8_dispatches_per_step": n_disp // (t_steps + 2),
            "loss_baseline": round(loss_base, 4),
            "loss_fp8": round(loss_fp8, 4),
            # e4m3 pairs run the MXU at the int8 rate (ops/pallas_kernels.py)
            "nominal_v5e_matmul_speedup_x": round(
                V5E_INT8_TOPS / NOMINAL_BF16_TFLOPS, 2
            ),
            "bench_r06_bf16_tflops": r06_bf16,
        }
        return record
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def run_online_bench(smoke=False):
    """Online-learning evidence pass (PR 15 -> ONLINE.json; docs/online.md).

    One process, the full loop: a DeepFM CTR model trains on a synthetic
    clickstream (OnlineTrainer over the elastic Supervisor), publishing a
    base + delta chain into a model repository every `interval` steps, while
    a ModelServer serves the SAME model to concurrent HTTP clients and a
    HotReloader lands each published version in the live engine. Proves:

      - zero 5xx across >= `swaps_target` hot swaps under load;
      - every response names the version that computed it, and each
        client's observed version sequence is monotone;
      - staleness stays under the contract bound (gauge sampled all run);
      - bit-parity: for sampled versions k, an OFFLINE engine restored from
        base+deltas(<=k) reproduces the served prediction exactly;
      - sustained trainer rows/sec while serving.
    """
    import io as stdio  # noqa: F401  (kept for parity with serving bench)
    import shutil
    import tempfile
    import threading
    import urllib.error
    import urllib.request

    import paddle_tpu.fluid as fluid
    from paddle_tpu import framework
    from paddle_tpu.executor import Scope, scope_guard
    from paddle_tpu.models.deepfm import deepfm
    from paddle_tpu.observability import registry as _registry
    from paddle_tpu.online import (
        HotReloader,
        ModelPublisher,
        OnlineTrainer,
        StalenessContract,
        read_latest,
    )
    from paddle_tpu.resilience import async_ckpt as ac
    from paddle_tpu.serving import ModelServer, ServingEngine

    rows = 512 if smoke else 4096
    fields, dim, batch = 4, 8, 32
    interval = 5
    swaps_target = 3 if smoke else 10
    steps = interval * (swaps_target + 2)
    contract = StalenessContract(max_staleness_steps=10 * interval)

    work = tempfile.mkdtemp(prefix="online-bench-")
    repo = os.path.join(work, "repo")
    record = {
        "metric": "online_learning",
        "mode": "smoke" if smoke else "full",
        "table_rows": rows,
        "num_fields": fields,
        "batch_size": batch,
        "publish_interval": interval,
        "max_staleness_steps": contract.max_staleness_steps,
    }
    try:
        main_p, startup = framework.Program(), framework.Program()
        with fluid.unique_name.guard(), fluid.program_guard(main_p, startup):
            ids = fluid.layers.data(
                name="ids", shape=[fields, 1], dtype="int64"
            )
            label = fluid.layers.data(
                name="label", shape=[1], dtype="float32"
            )
            loss, pred, _ = deepfm(
                ids, label, num_features=rows, num_fields=fields,
                embedding_size=dim, layer_sizes=(16,),
                is_sparse=True, use_distributed=True,
            )
            fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)

        exe = fluid.Executor()
        scope = Scope(seed=0)
        model_dir = os.path.join(work, "model")
        with scope_guard(scope):
            exe.run(startup)
            fluid.io.save_inference_model(
                model_dir, ["ids"], [pred], exe, main_program=main_p
            )

        srv = ModelServer(port=0)
        eng = srv.add_model(
            "ctr", model_dir, batch_buckets=(1, 2, 4),
            batcher_opts={"max_batch_delay_ms": 1.0},
        )
        serve_names = eng.param_names()
        port = srv.start()
        base_url = "http://127.0.0.1:%d" % port

        trainer = OnlineTrainer(
            exe, main_p, repo, serve_names,
            publisher=ModelPublisher(
                repo, max_chain=steps, contract=contract
            ),
            publish_interval=interval, scope=scope,
        )
        reloader = HotReloader(
            repo, {"ctr": eng}, consumer="bench", poll_interval_s=0.02,
            contract=contract,
        ).start()

        def stream():
            rng = np.random.RandomState(11)
            for _ in range(steps):
                yield {
                    "ids": rng.randint(
                        0, rows, (batch, fields, 1)
                    ).astype(np.int64),
                    "label": (
                        rng.rand(batch, 1) < 0.5
                    ).astype(np.float32),
                }

        train_curve = []
        train_wall = []

        def train():
            t0 = time.perf_counter()
            train_curve.extend(
                trainer.run(stream(), fetch_list=[loss.name])
            )
            train_wall.append(time.perf_counter() - t0)

        payload = json.dumps({
            "inputs": {
                "ids": np.random.RandomState(5).randint(
                    0, rows, (2, fields, 1)
                ).tolist()
            }
        }).encode()
        stop = threading.Event()
        n_clients = 3
        per_client = [[] for _ in range(n_clients)]  # (version, outputs)
        errors_5xx, errors_other = [], []
        staleness_seen = []

        def client(i):
            while not stop.is_set():
                try:
                    req = urllib.request.Request(
                        base_url + "/v1/models/ctr:predict", data=payload,
                        headers={"Content-Type": "application/json"},
                    )
                    doc = json.load(urllib.request.urlopen(req, timeout=30))
                    out = np.asarray(
                        list(doc["outputs"].values())[0], np.float32
                    )
                    per_client[i].append((int(doc["model_version"]), out))
                except urllib.error.HTTPError as e:
                    (errors_5xx if e.code >= 500 else errors_other).append(e)
                except Exception as e:
                    errors_other.append(e)

        def sample_staleness():
            snap = _registry.default_registry().snapshot()
            vals = snap.get("online/serving_staleness_steps", {})
            for v in (vals.get("values") or {}).values():
                staleness_seen.append(float(v))

        tthread = threading.Thread(target=train, daemon=True)
        cthreads = [
            threading.Thread(target=client, args=(i,), daemon=True)
            for i in range(n_clients)
        ]
        tthread.start()
        for t in cthreads:
            t.start()
        while tthread.is_alive():
            tthread.join(0.05)
            sample_staleness()
        # let the reloader land the final version, then stop the load
        deadline = time.perf_counter() + 30
        while time.perf_counter() < deadline:
            latest = read_latest(repo)
            if latest and reloader.applied_version == latest["version"]:
                break
            time.sleep(0.05)
            sample_staleness()
        time.sleep(0.2)  # a few requests against the final version
        stop.set()
        for t in cthreads:
            t.join(30)
        reloader.stop()
        srv.stop(drain=True)

        samples = [s for cs in per_client for s in cs]
        versions = sorted({v for v, _ in samples})
        for cs in per_client:  # served version is monotone per client
            vs = [v for v, _ in cs]
            assert vs == sorted(vs), "served version went backwards"
        assert not errors_5xx, errors_5xx[:3]
        assert reloader.reloads >= swaps_target, (
            "only %d hot swaps" % reloader.reloads
        )
        latest = read_latest(repo)
        assert latest and reloader.applied_version == latest["version"]
        assert max(staleness_seen or [0.0]) <= contract.max_staleness_steps

        # bit-parity: offline engine from base+deltas(<=k) == served output
        by_version = {}
        for v, out in samples:
            by_version.setdefault(v, out)
        check = [v for v in versions if v > 0][-4:]
        feed = {
            "ids": np.asarray(
                json.loads(payload)["inputs"]["ids"], np.int64
            )
        }
        parity = True
        for k in check:
            step_k, arrays, _info = ac.load_with_deltas(repo, upto_step=k)
            assert step_k == k
            off = ServingEngine(
                model_dir, name="off%d" % k, batch_buckets=(1, 2, 4)
            )
            off.set_params(arrays, version=k)
            (out_k,) = off.run(feed)
            parity = parity and np.array_equal(
                np.asarray(out_k, np.float32), by_version[k]
            )
        assert parity, "served prediction != offline base+delta replay"

        wall = train_wall[0] if train_wall else float("nan")
        pub = trainer.publisher.stats()
        record.update({
            "train_steps": trainer.steps,
            "train_wall_s": round(wall, 3),
            "rows_per_sec": round(trainer.steps * batch / wall, 1),
            "loss_first": round(train_curve[0], 5) if train_curve else None,
            "loss_last": round(train_curve[-1], 5) if train_curve else None,
            "publishes": pub["published"],
            "publish_throttled": pub["throttled"],
            "delta_chain_len": pub["chain_len"],
            "hot_swaps": reloader.reloads,
            "reload_errors": reloader.errors,
            "requests_total": len(samples),
            "errors_5xx": len(errors_5xx),
            "errors_other": len(errors_other),
            "versions_served": versions,
            "max_staleness_steps_observed": max(staleness_seen or [0.0]),
            "parity_versions_checked": check,
            "parity_bit_exact": bool(parity),
        })
    finally:
        shutil.rmtree(work, ignore_errors=True)
    return record


def run_fleet_bench(smoke=False):
    """Fleet chaos soak (PR 16 -> FLEET.json; docs/fleet.md).

    Three replica ModelServer SUBPROCESSES (predict MLP + a tiny GPTDecoder
    :generate model, all replicas seeded identically) behind one Router,
    under live mixed predict/generate client traffic. Mid-run, one replica
    is SIGKILLed and later restarted; it may rejoin the routable pool only
    after its HotReloader lands AND acks the repository's published model
    version (the PR 15 staleness gate). Then two targeted chaos rounds —
    PADDLE_TPU_FAULTS=conn_reset and slow_response armed on ONE replica —
    must show that replica's circuit breaker opening and re-closing while
    the router absorbs everything. Acceptance, asserted here:

      - zero 5xx across the whole soak; served_fraction == 1.0;
      - failover-window p99 <= 5x steady-state p99;
      - the killed replica rejoins only at/after the acked target version;
      - breaker opened >= 1x and re-closed in each targeted chaos round,
        with zero client-visible errors.
    """
    import shutil
    import tempfile
    import threading
    import urllib.error
    import urllib.request

    import paddle_tpu.fluid as fluid
    from paddle_tpu import framework
    from paddle_tpu.executor import Scope, scope_guard
    from paddle_tpu.fleet import CLOSED, ReplicaProcess, Router
    from paddle_tpu.online import ModelPublisher, read_latest
    from paddle_tpu.serving import ServingEngine

    steady_s = 2.0 if smoke else 6.0
    chaos_s = 2.0 if smoke else 5.0
    n_predict_clients = 3
    n_generate_clients = 2

    work = tempfile.mkdtemp(prefix="fleet-bench-")
    repo = os.path.join(work, "repo")
    record = {
        "metric": "fleet_chaos",
        "mode": "smoke" if smoke else "full",
        "replicas": 3,
    }
    gen_kw = dict(vocab_size=24, n_layer=2, n_head=2, d_model=16,
                  d_inner=32, max_context=16)

    def _save_mlp_inference(model_dir):
        main_p, startup = framework.Program(), framework.Program()
        with fluid.unique_name.guard(), fluid.program_guard(main_p, startup):
            x = fluid.layers.data(name="fx", shape=[6], dtype="float32")
            h = fluid.layers.fc(input=x, size=8, act="relu")
            y = fluid.layers.fc(input=h, size=3, act="softmax")
        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope(seed=3)):
            exe.run(startup)
            fluid.io.save_inference_model(
                model_dir, ["fx"], [y], exe, main_program=main_p
            )

    def _spec(name):
        return {
            "name": name,
            "request_timeout_ms": 10000.0,
            "predict": {"model": "m", "model_dir": model_dir},
            "generate": {"model": "g", "model_kw": gen_kw, "seed": 0,
                         "max_slots": 3, "page_size": 4, "max_context": 16},
            "repo": repo,
            "poll_interval_s": 0.1,
        }

    p_doc = json.dumps({
        "inputs": {"fx": np.random.RandomState(9).rand(2, 6).tolist()}
    }).encode()
    g_doc = json.dumps({
        "prompt": [1, 2, 3], "max_new_tokens": 4, "eos_id": 999
    }).encode()

    def _post(url, body, timeout=30.0):
        req = urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/json"}
        )
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read().decode())

    try:
        model_dir = os.path.join(work, "model")
        _save_mlp_inference(model_dir)
        # publish v1 into the repo: replicas must land+ack it to be routable
        eng = ServingEngine(model_dir, name="m", batch_buckets=(1, 2, 4))
        params = {n: np.asarray(eng.scope.vars[n]).copy()
                  for n in eng.param_names()}
        ModelPublisher(repo).publish(params, 1)
        target_version = read_latest(repo)["version"]

        reps = [ReplicaProcess(_spec("fr%d" % i), work) for i in range(3)]
        router = Router(
            port=0, hedge=True, hedge_delay_ms=80.0, probe_interval_s=0.2,
            down_after=2, total_deadline_s=20.0, attempt_timeout_s=8.0,
            repo=repo, repo_model="m", seed=0,
        )
        rport = router.start()
        base = "http://127.0.0.1:%d" % rport
        for r in reps:
            r.start()
        for r in reps:
            r.wait_ready(timeout=300.0)
            router.register(r.name, r.url)
        router.probe_once()
        assert len(router.stats()["routable"]) == 3, router.stats()

        phase = ["steady"]
        samples = []  # (phase, kind, latency_s, code)
        errors_5xx, errors_other = [], []
        gen_tokens = set()
        stop = threading.Event()

        def client(kind):
            url = base + ("/v1/models/m:predict" if kind == "predict"
                          else "/v1/models/g:generate")
            body = p_doc if kind == "predict" else g_doc
            while not stop.is_set():
                t0 = time.perf_counter()
                try:
                    code, doc = _post(url, body)
                    samples.append(
                        (phase[0], kind, time.perf_counter() - t0, code)
                    )
                    if kind == "generate":
                        gen_tokens.add(tuple(doc["tokens"]))
                except urllib.error.HTTPError as e:
                    (errors_5xx if e.code >= 500 else errors_other).append(
                        (phase[0], kind, e.code)
                    )
                except Exception as e:
                    errors_5xx.append((phase[0], kind, repr(e)))

        threads = [threading.Thread(target=client, args=("predict",),
                                    daemon=True)
                   for _ in range(n_predict_clients)]
        threads += [threading.Thread(target=client, args=("generate",),
                                     daemon=True)
                    for _ in range(n_generate_clients)]
        for t in threads:
            t.start()

        time.sleep(steady_s)
        # ------------------------------------------------ SIGKILL + restart
        phase[0] = "failover"
        reps[0].kill()
        t_kill = time.perf_counter()
        time.sleep(chaos_s)
        reps[0].restart()
        reps[0].wait_ready(timeout=300.0)
        router.register(reps[0].name, reps[0].url)  # fresh port
        # the staleness gate: routable again only once the restarted
        # process's HotReloader has ACKED the published version
        rejoin_deadline = time.monotonic() + 120.0
        rejoined_at_version = None
        while time.monotonic() < rejoin_deadline:
            router.probe_once()
            if reps[0].name in router.stats()["routable"]:
                rejoined_at_version = router.replicas()[
                    reps[0].name
                ].version_for_gate("m")
                break
            time.sleep(0.1)
        assert rejoined_at_version is not None, "killed replica never rejoined"
        assert rejoined_at_version >= target_version
        phase[0] = "recovered"
        time.sleep(steady_s / 2)
        stop.set()
        for t in threads:
            t.join(30.0)

        total = len(samples) + len(errors_5xx) + len(errors_other)
        served = len(samples)
        lat = {ph: sorted(s[2] for s in samples if s[0] == ph)
               for ph in ("steady", "failover", "recovered")}
        p99 = {
            ph: (xs[min(int(len(xs) * 0.99), len(xs) - 1)] * 1e3
                 if xs else None)
            for ph, xs in lat.items()
        }
        assert not errors_5xx, errors_5xx[:5]
        assert served == total and total > 0
        assert len(gen_tokens) == 1, (
            "generate replicas disagreed: %s" % gen_tokens
        )
        failover_ratio = (
            p99["failover"] / p99["steady"]
            if p99["failover"] and p99["steady"] else None
        )
        assert failover_ratio is None or failover_ratio <= 5.0, (
            "failover p99 %.1fms > 5x steady p99 %.1fms"
            % (p99["failover"], p99["steady"])
        )
        record.update({
            "requests_total": total,
            "served_fraction": round(served / total, 4),
            "errors_5xx": len(errors_5xx),
            "errors_other": len(errors_other),
            "steady_p99_ms": round(p99["steady"], 2) if p99["steady"] else None,
            "failover_p99_ms": (
                round(p99["failover"], 2) if p99["failover"] else None
            ),
            "failover_p99_over_steady": (
                round(failover_ratio, 2) if failover_ratio else None
            ),
            "recovered_p99_ms": (
                round(p99["recovered"], 2) if p99["recovered"] else None
            ),
            "target_model_version": target_version,
            "rejoined_at_version": rejoined_at_version,
            "kill_to_stop_s": round(time.perf_counter() - t_kill, 2),
            "retries": router._m_retries.value(kind="predict")
            + router._m_retries.value(kind="generate"),
            "hedges_launched": router._m_hedges.value(event="launched"),
            "hedges_won": router._m_hedges.value(event="won"),
            "generate_parity": len(gen_tokens) == 1,
        })
        router.stop()
        for r in reps:
            r.terminate()

        # ---------------------------------------- targeted breaker rounds
        # one replica armed with a deterministic fault plan, one clean: the
        # armed replica's breaker must open AND re-close while every client
        # request still succeeds through failover
        for fault_kind, fault_spec in (
            ("conn_reset", "conn_reset:every=2"),
            ("slow_response", "slow_response:every=2@ms=1200"),
        ):
            cr = [
                ReplicaProcess(_spec("%s0" % fault_kind[:2]), work,
                               faults=fault_spec),
                ReplicaProcess(_spec("%s1" % fault_kind[:2]), work),
            ]
            crouter = Router(
                port=0, hedge=False, probe_interval_s=0.2,
                total_deadline_s=20.0, attempt_timeout_s=0.4,
                repo=repo, repo_model="m", seed=1,
                breaker_opts=dict(
                    failure_threshold=3, error_rate_threshold=0.5,
                    min_requests=4, open_for_s=0.3, success_threshold=1,
                ),
            )
            cport = crouter.start()
            armed = cr[0].spec["name"]
            try:
                for r in cr:
                    r.start()
                for r in cr:
                    r.wait_ready(timeout=300.0)
                    crouter.register(r.name, r.url)
                crouter.probe_once()
                url = "http://127.0.0.1:%d/v1/models/m:predict" % cport
                codes = []
                opened = closed_again = False
                deadline = time.monotonic() + (20.0 if smoke else 40.0)
                while time.monotonic() < deadline:
                    codes.append(_post(url, p_doc)[0])
                    br = crouter.replicas()[armed].breaker
                    if br.stats()["opens"] >= 1:
                        opened = True
                        if br.state == CLOSED:
                            closed_again = True
                            break
                    time.sleep(0.01)
                assert codes and all(c == 200 for c in codes), (
                    fault_kind, codes[-5:]
                )
                assert opened, "%s never tripped the breaker" % fault_kind
                assert closed_again, (
                    "%s breaker never re-closed" % fault_kind
                )
                record["%s_requests" % fault_kind] = len(codes)
                record["%s_breaker_opens" % fault_kind] = (
                    crouter.replicas()[armed].breaker.stats()["opens"]
                )
                record["%s_client_errors" % fault_kind] = 0
                record["%s_breaker_reclosed" % fault_kind] = True
            finally:
                crouter.stop()
                for r in cr:
                    try:
                        r.kill()
                    except Exception:
                        pass
    finally:
        shutil.rmtree(work, ignore_errors=True)
    return record


def run_tracing_bench(smoke=False):
    """Tracing + flight-recorder evidence pass (ISSUE 19 -> TRACING.json).

    Two measurements:

    1. **Overhead**: sustained single-client load on an MLP through
       ServingEngine + ContinuousBatcher, tracing OFF vs ON (sample 1.0 —
       the worst case: every span exported). Acceptance: p99 with tracing
       on regresses <= 5% vs off (asserted in full mode; best-of-N rounds
       per config damp CPU scheduling noise).

    2. **Chaos propagation**: three replica ModelServer subprocesses behind
       the Router, all four processes tracing into ONE shared trace dir.
       One replica is armed with PADDLE_TPU_FAULTS=conn_reset (failed
       attempts + failover) and later SIGKILLed (breaker opens). Acceptance:
       served_fraction == 1.0; flight-recorder bundles exist whose span
       ring shows a failed router.attempt AND the successful failover
       under the SAME trace id; at least one trace's spans come from >= 3
       distinct OS processes (router + failed replica + winning replica);
       tools/timeline.py --trace_path and tools/trace_view.py both render
       the shards.
    """
    import shutil
    import tempfile
    import threading

    from paddle_tpu import flags as _flags
    from paddle_tpu import fluid
    from paddle_tpu import framework
    from paddle_tpu.executor import Scope, scope_guard
    from paddle_tpu.fleet import ReplicaProcess, Router
    from paddle_tpu.observability import flightrec as _flightrec
    from paddle_tpu.observability import tracing as _tracing
    from paddle_tpu.serving import ContinuousBatcher, ServingEngine

    work = tempfile.mkdtemp(prefix="tracing-bench-")
    record = {"metric": "tracing", "mode": "smoke" if smoke else "full"}
    old_flags = _flags.get_flags([
        "trace_dir", "flightrec_dir", "trace_sample", "flightrec_min_interval_s",
    ])

    def _save_mlp_inference(model_dir):
        # wide enough that a request carries real engine compute (~ms):
        # against a micro-model the bound would measure interpreter call
        # overhead per span vs a degenerate denominator no deployment has
        main_p, startup = framework.Program(), framework.Program()
        with fluid.unique_name.guard(), fluid.program_guard(main_p, startup):
            x = fluid.layers.data(name="tx", shape=[6], dtype="float32")
            h = fluid.layers.fc(input=x, size=64, act="relu")
            h = fluid.layers.fc(input=h, size=64, act="relu")
            y = fluid.layers.fc(input=h, size=3, act="softmax")
        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope(seed=3)):
            exe.run(startup)
            fluid.io.save_inference_model(
                model_dir, ["tx"], [y], exe, main_program=main_p
            )

    def _set_tracing(trace_dir, flightrec_dir=""):
        _flags.set_flags({
            "trace_dir": trace_dir, "flightrec_dir": flightrec_dir,
            "trace_sample": 1.0, "flightrec_min_interval_s": 0.1,
        })
        _tracing.reset()
        _flightrec.reset()

    try:
        model_dir = os.path.join(work, "model")
        _save_mlp_inference(model_dir)

        # ---- 1. overhead: p99 with tracing off vs on ----------------------
        n_requests = 200 if smoke else 800
        rounds = 1 if smoke else 5
        feed = {"tx": np.random.RandomState(7).rand(2, 6).astype("float32")}

        n_clients = 8

        def measure(trace_dir):
            # closed-loop concurrent clients — the shape the fleet actually
            # serves: per-batch spans (serving.batch, engine.execute) and
            # the segment serialization amortize across the co-batched
            # requests, exactly as they do behind the router
            _set_tracing(trace_dir)
            eng = ServingEngine(model_dir, name="tb",
                                batch_buckets=(1, 2, 4, 8, 16))
            b = ContinuousBatcher(eng, max_queue_rows=256,
                                  max_batch_delay_ms=1.0)
            lats = []
            lats_lock = threading.Lock()

            def client(n):
                mine = []
                for _ in range(n):
                    t0 = time.perf_counter()
                    b.run(dict(feed), timeout=30.0)
                    mine.append(time.perf_counter() - t0)
                with lats_lock:
                    lats.extend(mine)

            try:
                b.run(dict(feed), timeout=30.0)  # warmup/trace
                threads = [
                    threading.Thread(
                        target=client, args=(n_requests // n_clients,)
                    )
                    for _ in range(n_clients)
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
            finally:
                b.close()
            return lats

        def _p99(lats):
            lats = sorted(lats)
            return lats[min(int(len(lats) * 0.99), len(lats) - 1)] * 1e3

        # interleave configs so machine-load drift penalizes both equally
        # (all-off-then-all-on attributes any slow patch to "on"), and POOL
        # the rounds before taking p99: both configs then see the same noise
        # environment, instead of min-of-rounds rewarding one lucky round
        # interleaved off/on rounds so machine-load drift penalizes both
        # equally, gated on the MEDIAN of per-round p99s: robust to one
        # noisy round on either side (a single scheduler hiccup routinely
        # moves a round's p99 by 50% on a shared host), still a p99 bound
        # one discarded warmup pass per config: the first tracing-enabled
        # round pays one-time costs (shard dir, writer thread, code paths)
        # that are not steady-state overhead
        measure("")
        measure(os.path.join(work, "ovh-traces-warm"))
        rounds_off, rounds_on = [], []
        for i in range(rounds):
            rounds_off.append(round(_p99(measure("")), 3))
            rounds_on.append(round(
                _p99(measure(os.path.join(work, "ovh-traces-%d" % i))), 3
            ))
            print("  overhead round %d: p99 off=%.3fms on=%.3fms"
                  % (i, rounds_off[-1], rounds_on[-1]))
        p99_off = sorted(rounds_off)[len(rounds_off) // 2]
        p99_on = sorted(rounds_on)[len(rounds_on) // 2]
        record["p99_rounds_off"] = rounds_off
        record["p99_rounds_on"] = rounds_on
        overhead_pct = 100.0 * (p99_on - p99_off) / p99_off
        record.update({
            "p99_ms_tracing_off": round(p99_off, 3),
            "p99_ms_tracing_on": round(p99_on, 3),
            "overhead_pct": round(overhead_pct, 2),
        })
        if not smoke:
            assert p99_on <= p99_off * 1.05, (
                "tracing-on p99 %.3fms > 1.05x off p99 %.3fms"
                % (p99_on, p99_off)
            )

        # ---- 2. chaos propagation across real processes -------------------
        tdir = os.path.join(work, "traces")
        fdir = os.path.join(work, "flightrec")
        trace_env = {
            "FLAGS_trace_dir": tdir,
            "FLAGS_flightrec_dir": fdir,
            "FLAGS_trace_sample": "1.0",
        }
        spec = lambda name: {
            "name": name,
            "request_timeout_ms": 10000.0,
            "predict": {"model": "m", "model_dir": model_dir},
        }
        reps = [
            ReplicaProcess(spec("tr0"), work, env=dict(trace_env),
                           faults="conn_reset:every=3"),
            ReplicaProcess(spec("tr1"), work, env=dict(trace_env)),
            ReplicaProcess(spec("tr2"), work, env=dict(trace_env)),
        ]
        _set_tracing(tdir, fdir)  # router traces + records in-process
        router = Router(
            port=0, hedge=False, probe_interval_s=0.2, down_after=2,
            total_deadline_s=20.0, attempt_timeout_s=8.0, seed=0,
            breaker_opts=dict(failure_threshold=2, error_rate_threshold=0.5,
                              min_requests=2, open_for_s=0.3,
                              success_threshold=1),
        )
        rport = router.start()
        codes = []
        try:
            for r in reps:
                r.start()
            for r in reps:
                r.wait_ready(timeout=300.0)
                router.register(r.name, r.url)
            router.probe_once()
            assert len(router.stats()["routable"]) == 3, router.stats()

            url = "http://127.0.0.1:%d/v1/models/m:predict" % rport
            doc = json.dumps({
                "inputs": {"tx": np.random.RandomState(1).rand(2, 6).tolist()}
            }).encode()

            import urllib.request

            def post():
                req = urllib.request.Request(
                    url, data=doc,
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(req, timeout=30.0) as resp:
                    resp.read()
                    return resp.status

            n_round1 = 30 if smoke else 120
            n_round2 = 20 if smoke else 60
            for _ in range(n_round1):  # conn_reset round: failovers + breaker
                codes.append(post())
            reps[0].kill()             # SIGKILL round: DOWN + more failovers
            for _ in range(n_round2):
                codes.append(post())
        finally:
            router.stop()
            for r in reps:
                try:
                    r.kill()
                except Exception:
                    pass
            _tracing.reset()   # flush the router's shard
            _flightrec.reset()
            _flags.set_flags(old_flags)
            _tracing.reset()
            _flightrec.reset()

        served_fraction = sum(c == 200 for c in codes) / float(len(codes))
        assert served_fraction == 1.0, (
            "%d/%d served" % (sum(c == 200 for c in codes), len(codes))
        )

        spans = _tracing.load_spans(tdir)
        by_trace = {}
        for s in spans:
            by_trace.setdefault(s["trace"], []).append(s)
        # a failover trace: failed attempt + ok attempt, spans from >= 3 pids
        multi = None
        for tid, sp in by_trace.items():
            names = [s["name"] for s in sp]
            att = [s for s in sp if s["name"] == "router.attempt"]
            pids = {(s.get("host"), s.get("pid")) for s in sp}
            if (len(pids) >= 3 and "server.request" in names
                    and any(a["status"] == "error" for a in att)
                    and any(a["status"] == "ok" for a in att)):
                multi = (tid, sorted(str(p) for p in pids), len(sp))
                break
        assert multi is not None, (
            "no failover trace spanning >= 3 processes found "
            "(%d traces, %d spans)" % (len(by_trace), len(spans))
        )

        bundles = sorted(
            d for d in os.listdir(fdir) if d.startswith("bundle-")
        )
        assert bundles, "chaos run produced no flight-recorder bundles"
        reasons = {b.split("-")[2] for b in bundles}
        # a bundle whose span ring shows failed attempt + failover, same trace
        bundle_failover = False
        for b in bundles:
            ring = _tracing.load_spans(os.path.join(fdir, b, "spans.jsonl"))
            ring_tr = {}
            for s in ring:
                ring_tr.setdefault(s["trace"], []).append(s)
            for sp in ring_tr.values():
                att = [s for s in sp if s["name"] == "router.attempt"]
                if (any(a["status"] == "error" for a in att)
                        and any(a["status"] == "ok" for a in att)):
                    bundle_failover = True
                    break
            if bundle_failover:
                break
        assert bundle_failover, (
            "no bundle's span ring shows failed attempt + failover: %s"
            % bundles
        )

        # ---- render checks: timeline + trace_view over the shards ---------
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "tools"))
        import timeline as _timeline
        import trace_view as _trace_view

        tl_path = os.path.join(work, "timeline.json")
        n_events = _timeline.convert(
            "", tl_path, trace_path=tdir
        )
        assert n_events >= len(spans)
        assert _trace_view.main([tdir, "--top", "5"]) == 0
        assert _trace_view.main([tdir, "--trace", multi[0]]) == 0

        record.update({
            "requests": len(codes),
            "served_fraction": served_fraction,
            "traces": len(by_trace),
            "spans": len(spans),
            "failover_trace": multi[0],
            "failover_trace_processes": len(multi[1]),
            "failover_trace_spans": multi[2],
            "bundles": len(bundles),
            "bundle_reasons": sorted(reasons),
            "bundle_shows_failover": bundle_failover,
            "timeline_events": n_events,
        })
    finally:
        _flags.set_flags(old_flags)
        _tracing.reset()
        _flightrec.reset()
        shutil.rmtree(work, ignore_errors=True)
    return record


def run_slo_bench(smoke=False):
    """Fleet SLO-engine evidence pass (ISSUE 20 -> SLO.json).

    Five measurements:

    1. **Exactness**: ``promparse.parse(registry.to_prometheus()) ==
       registry.snapshot()`` for populated registries, and fleet p50/p99
       computed from the bucket-wise merge of three replicas' expositions
       are BIT-EQUAL to the percentiles of one pooled histogram that saw
       every raw observation (same grid, same interpolation arithmetic).
    2. **Steady state**: two clean replica subprocesses behind
       Router(fleet_metrics=True) with availability + latency SLOs on
       compressed burn-rate windows and all three sentinel kinds armed —
       ZERO alerts may fire, and the goodput gauge tracks the roofline
       measured during warmup (MFU-online ~ 1.0).
    3. **Chaos**: a pre-booted replica armed with
       PADDLE_TPU_FAULTS=slow_response (+400 ms per request, below the
       attempt timeout so availability stays clean while latency burns)
       is swapped IN for the clean pair — the "bad deploy rolled out"
       shape. The fast-burn page alert on the latency SLO must fire
       < 60 s after the swap, a matching ``slo_alert`` flight-recorder
       bundle (carrying the offending window's merged series) must land
       on disk, and the alert must RESOLVE after the clean pair is
       swapped back. tools/timeline.py renders the alert track.
    4. **Hot-swap drift**: an in-process LocalSampler + DriftSentinel over
       a serving latency histogram — a stationary phase fires nothing,
       then the engine is swapped for a much heavier model and the EWMA
       sentinel catches the regression no static threshold would.
    5. **Overhead**: router client p99 with the scrape+eval loop ON
       (aggressive 0.25 s interval, SLOs + sentinels evaluated every
       scrape) vs OFF — interleaved rounds, gated on the median of
       per-round p99s: on <= 1.05x off (asserted in full mode).
    """
    import glob
    import shutil
    import tempfile
    import threading
    import urllib.error
    import urllib.request

    from paddle_tpu import flags as _flags
    from paddle_tpu import fluid
    from paddle_tpu import framework
    from paddle_tpu.executor import Scope, scope_guard
    from paddle_tpu.fleet import ReplicaProcess, Router
    from paddle_tpu.observability import flightrec as _flightrec
    from paddle_tpu.observability import promparse
    from paddle_tpu.observability import registry as _obsreg
    from paddle_tpu.observability.aggregate import (
        hist_percentile,
        merge_snapshots,
    )
    from paddle_tpu.observability.slo import (
        SLO,
        AlertEngine,
        BurnRateRule,
        DriftSentinel,
        GoodputSentinel,
        LocalSampler,
        RetraceSentinel,
    )
    from paddle_tpu.serving import ServingEngine

    work = tempfile.mkdtemp(prefix="slo-bench-")
    record = {"metric": "slo", "mode": "smoke" if smoke else "full"}
    old_flags = _flags.get_flags([
        "flightrec_dir", "flightrec_min_interval_s",
    ])

    # ---- 1. exposition round trip + merged-percentile bit-equality --------
    rng = np.random.RandomState(11)
    regs = [_obsreg.MetricRegistry() for _ in range(3)]
    pooled = _obsreg.MetricRegistry().histogram(
        "serving/latency_ms", "pooled reference: every raw observation"
    )
    for i, reg in enumerate(regs):
        reg.counter("fleet/requests", "routed").inc(
            7 * (i + 1), kind="predict", code="200"
        )
        h = reg.histogram("serving/latency_ms", "per-replica latency")
        for v in rng.gamma(2.0, 30.0, size=300 + 131 * i):
            h.observe(float(v))
            pooled.observe(float(v))
    parsed = [("rep%d" % i, promparse.parse(reg.to_prometheus()))
              for i, reg in enumerate(regs)]
    roundtrip = all(
        snap == regs[i].snapshot() for i, (_, snap) in enumerate(parsed)
    )
    fleet_rec = merge_snapshots(parsed)["serving/latency_ms"]
    pcts = {
        "p50": (hist_percentile(fleet_rec, 50), pooled.percentile(50)),
        "p99": (hist_percentile(fleet_rec, 99), pooled.percentile(99)),
    }
    merge_exact = all(a == b for a, b in pcts.values())
    record["roundtrip_exact"] = bool(roundtrip)
    record["merged_p99_bit_equal"] = bool(merge_exact)
    record["merged_vs_pooled"] = {
        k: {"merged": a, "pooled": b} for k, (a, b) in pcts.items()
    }
    # pure arithmetic, deterministic: asserted in smoke too
    assert roundtrip, "parse(to_prometheus()) != snapshot()"
    assert merge_exact, "merged percentiles not bit-equal: %r" % pcts

    def _save_mlp_inference(model_dir):
        main_p, startup = framework.Program(), framework.Program()
        with fluid.unique_name.guard(), fluid.program_guard(main_p, startup):
            x = fluid.layers.data(name="fx", shape=[6], dtype="float32")
            h = fluid.layers.fc(input=x, size=8, act="relu")
            y = fluid.layers.fc(input=h, size=3, act="softmax")
        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope(seed=3)):
            exe.run(startup)
            fluid.io.save_inference_model(
                model_dir, ["fx"], [y], exe, main_program=main_p
            )

    model_dir = os.path.join(work, "model")
    _save_mlp_inference(model_dir)

    # predict-only replicas: the SLO rounds exercise the scrape/alert
    # plane, not the engines, so the smallest servable model does
    def _spec(name):
        return {
            "name": name,
            "request_timeout_ms": 10000.0,
            "predict": {"model": "m", "model_dir": model_dir},
            "poll_interval_s": 0.1,
        }

    p_doc = json.dumps({
        "inputs": {"fx": np.random.RandomState(9).rand(2, 6).tolist()}
    }).encode()

    def _post(url, body, timeout=30.0):
        req = urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/json"}
        )
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read().decode())

    def _p99(vals):
        vals = sorted(vals)
        return vals[min(int(len(vals) * 0.99), len(vals) - 1)] * 1e3

    # compressed SRE-workbook rules: same two-window/two-burn structure,
    # seconds instead of hours, so one bench round watches a full
    # fire -> resolve cycle (DEFAULT_RULES would need 5m of history)
    def _rules():
        return [
            BurnRateRule("page", 4.0, 12.0, 8.0),
            BurnRateRule("ticket", 8.0, 24.0, 4.0),
        ]

    def _slos():
        return [
            SLO("availability", 0.999, counter="fleet/requests",
                bad={"code": "5"}, min_events=8,
                description="non-5xx fraction of routed requests"),
            SLO("latency", 0.99, histogram="fleet/request_ms",
                threshold_ms=100.0, min_events=8,
                description="routed requests under 100 ms"),
        ]

    def _sentinels():
        return [
            DriftSentinel("fleet_latency_drift", "fleet/request_ms",
                          warmup=10, rel_threshold=2.0),
            RetraceSentinel(steady_ticks=8),
        ]

    warm_s = 3.0 if smoke else 5.0
    steady_s = 6.0 if smoke else 15.0
    fdir = os.path.join(work, "flightrec")
    alerts_path = os.path.join(work, "alerts.jsonl")
    _flags.set_flags({
        # min_interval 0: drift + page alerts can fire on the SAME
        # evaluate tick and each must still get its bundle
        "flightrec_dir": fdir, "flightrec_min_interval_s": 0.0,
    })
    _flightrec.reset()

    reps = []
    router = None
    stop = threading.Event()
    threads = []
    try:
        # ---- 2+3. live fleet: steady state, then slow_response chaos ------
        clean = [ReplicaProcess(_spec("sr%d" % i), work) for i in range(2)]
        slow = ReplicaProcess(
            _spec("sr_slow"), work, faults="slow_response:every=1@ms=400"
        )
        reps = clean + [slow]
        for r in reps:  # the slow one boots NOW so the chaos swap is instant
            r.start()
        router = Router(
            port=0, hedge=False, probe_interval_s=0.2, down_after=2,
            total_deadline_s=20.0, attempt_timeout_s=8.0, seed=0,
            fleet_metrics=True, scrape_interval_s=0.4,
            slos=_slos(), sentinels=_sentinels(), alert_rules=_rules(),
            alerts_path=alerts_path,
        )
        base = "http://127.0.0.1:%d" % router.start()
        engine = router.alert_engine
        for r in clean:
            r.wait_ready(timeout=300.0)
            router.register(r.name, r.url)
        router.probe_once()
        assert len(router.stats()["routable"]) == 2, router.stats()

        phase = ["warmup"]
        samples = []  # (phase, latency_s, code-or-repr)
        lock = threading.Lock()

        def client():
            url = base + "/v1/models/m:predict"
            while not stop.is_set():
                t0 = time.perf_counter()
                try:
                    code, _ = _post(url, p_doc)
                except Exception as e:  # noqa: BLE001 - tallied, not fatal
                    code = repr(e)
                with lock:
                    samples.append((phase[0], time.perf_counter() - t0, code))

        threads = [threading.Thread(target=client, daemon=True)
                   for _ in range(6)]
        for t in threads:
            t.start()

        t0 = time.time()
        time.sleep(warm_s)
        with lock:
            n_warm = sum(1 for p, _, _ in samples if p == "warmup")
        roofline_rps = n_warm / (time.time() - t0)
        # each predict carries 2 rows -> roofline in rows/s, fed live into
        # slo/goodput_per_s + slo/goodput_vs_roofline (MFU-online)
        goodput = engine.add_sentinel(GoodputSentinel(
            "fleet_goodput", "fleet/requests",
            roofline_per_s=roofline_rps * 2.0, unit="rows", scale=2.0,
        ))

        phase[0] = "steady"
        ev_mark = len(engine.events)
        time.sleep(steady_s)
        steady_fired = [
            e for e in engine.events[ev_mark:] if e.state == "firing"
        ]
        with lock:
            n_steady = sum(1 for p, _, _ in samples if p == "steady")
        record["steady"] = {
            "duration_s": steady_s,
            "requests": n_steady,
            "alerts_fired": len(steady_fired),
            "roofline_rows_per_s": round(roofline_rps * 2.0, 1),
            "goodput_rows_per_s": goodput.last_per_s,
            "goodput_vs_roofline": goodput.last_frac,
        }
        assert not steady_fired, (
            "false alert(s) in steady state: %s"
            % [e.to_dict() for e in steady_fired]
        )

        # chaos: swap the slow replica IN for the clean pair — every
        # request now pays +400 ms (still < attempt timeout: no failover,
        # no 5xx — availability holds while the latency SLO burns)
        slow.wait_ready(timeout=300.0)
        phase[0] = "chaos"
        router.register(slow.name, slow.url)
        router.probe_once()
        for r in clean:
            router.deregister(r.name)
        t_chaos = time.time()

        fired_ev = None
        deadline = t_chaos + 60.0
        while time.time() < deadline and fired_ev is None:
            fired_ev = next(
                (e for e in list(engine.events)
                 if e.name == "latency" and e.severity == "page"
                 and e.state == "firing" and e.ts >= t_chaos), None)
            time.sleep(0.2)
        fired_after = None if fired_ev is None else fired_ev.ts - t_chaos
        goodput_chaos = goodput.last_frac  # read mid-chaos, before recovery

        # clear the fault: clean pair back in, slow replica out
        phase[0] = "clear"
        for r in clean:
            router.register(r.name, r.url)
        router.probe_once()
        router.deregister(slow.name)
        t_clear = time.time()
        resolved_ev = None
        deadline = t_clear + 90.0
        while time.time() < deadline and resolved_ev is None:
            resolved_ev = next(
                (e for e in list(engine.events)
                 if e.name == "latency" and e.severity == "page"
                 and e.state == "resolved" and e.ts >= t_clear), None)
            time.sleep(0.2)

        stop.set()
        for t in threads:
            t.join(timeout=30.0)

        bundles = sorted(glob.glob(os.path.join(fdir, "bundle-*")))
        page_bundle = None
        for b in bundles:
            if "-slo_alert-" not in os.path.basename(b):
                continue
            with open(os.path.join(b, "event.json")) as f:
                ev = json.load(f)
            info = ev.get("info", {})
            if info.get("name") == "latency" and info.get("series"):
                page_bundle = os.path.basename(b)
        drift_fired = any(
            e.name == "fleet_latency_drift" and e.state == "firing"
            for e in engine.events
        )
        with lock:
            chaos_lat = [s for p, s, c in samples if p == "chaos" and c == 200]
            err_5xx = sum(
                1 for _, _, c in samples
                if (isinstance(c, int) and c >= 500)
                or (not isinstance(c, int))
            )
        record["chaos"] = {
            "fired": fired_ev is not None,
            "fired_after_s": None if fired_after is None
            else round(fired_after, 2),
            "resolved": resolved_ev is not None,
            "resolved_after_s": None if resolved_ev is None
            else round(resolved_ev.ts - t_clear, 2),
            "chaos_p99_ms": round(_p99(chaos_lat), 1) if chaos_lat else None,
            "errors_5xx": err_5xx,
            "drift_sentinel_also_fired": drift_fired,
            "goodput_vs_roofline_during_chaos": goodput_chaos,
            "slo_alert_bundle": page_bundle,
            "alert_log_lines": sum(1 for _ in open(alerts_path))
            if os.path.exists(alerts_path) else 0,
        }
        assert fired_ev is not None and fired_after < 60.0, (
            "fast-burn latency page did not fire within 60s: %s"
            % record["chaos"]
        )
        assert resolved_ev is not None, (
            "latency page never resolved after the fault cleared"
        )
        assert page_bundle is not None, (
            "no slo_alert flight-recorder bundle with the merged series: %s"
            % bundles
        )

        # render check: the alert fire/resolve pairs become a chrome-trace
        # track (satellite: tools/timeline.py --alerts_path)
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "tools"))
        import timeline as _timeline

        tl_path = os.path.join(work, "timeline.json")
        n_tl = _timeline.convert("", tl_path, alerts_path=alerts_path)
        record["chaos"]["timeline_events"] = n_tl
        assert n_tl >= 1, "timeline rendered no alert events"

        router.stop()
        router = None
        reps.pop().terminate()  # the clean pair stays up for round 5

        # ---- 4. hot-swap latency drift (in-process) -----------------------
        sreg = _obsreg.MetricRegistry()
        shist = sreg.histogram("serving/swap_latency_ms", "client latency")
        sampler = LocalSampler(sreg)
        deng = AlertEngine(slos=(), history=sampler, rules=(),
                           registry=sreg, log_stderr=False, flightrec=False)
        # detection threshold 2x (rel 1.0): the swapped-in model is ~5x
        # heavier, so detection keeps headroom, while a scheduler hiccup
        # inside a 12-sample tick mean can't move the fast EWMA past 2x
        # the baseline (the 0.6 threshold false-fired on a shared host)
        drift = deng.add_sentinel(DriftSentinel(
            "hot_swap_drift", "serving/swap_latency_ms",
            warmup=6, rel_threshold=1.0, min_count=2,
        ))

        def _save_wide(out_dir, layers, width):
            main_p, startup = framework.Program(), framework.Program()
            with fluid.unique_name.guard(), \
                    fluid.program_guard(main_p, startup):
                x = fluid.layers.data(name="fx", shape=[6], dtype="float32")
                hh = x
                for _ in range(layers):
                    hh = fluid.layers.fc(input=hh, size=width, act="relu")
                y = fluid.layers.fc(input=hh, size=3, act="softmax")
            exe = fluid.Executor(fluid.CPUPlace())
            with scope_guard(Scope(seed=4)):
                exe.run(startup)
                fluid.io.save_inference_model(
                    out_dir, ["fx"], [y], exe, main_program=main_p
                )

        # baseline ~1.3 ms/call (6x1024): heavy enough that tick means on
        # a shared host stay within ~1.5x (a 2x64 micro-model's means
        # swing 5x on dispatch noise alone and false-fire any threshold
        # that could still catch a real swap); the "bad hot swap" lands an
        # 8x2048 stack in its place, ~5x slower per call
        small_dir = os.path.join(work, "model_small")
        big_dir = os.path.join(work, "model_big")
        _save_wide(small_dir, 6, 1024)
        _save_wide(big_dir, 8, 2048)
        small = ServingEngine(small_dir, name="dm", batch_buckets=(2,))
        big = ServingEngine(big_dir, name="dm_big", batch_buckets=(2,))
        feed = {"fx": np.random.RandomState(5).rand(2, 6).astype("float32")}
        for eng in (small, big):  # compile outside the measured stream
            eng.run(dict(feed))

        n_ticks = 20 if smoke else 40
        false_pos = 0

        def _tick(eng):
            for _ in range(12):
                tq = time.perf_counter()
                eng.run(dict(feed))
                shist.observe((time.perf_counter() - tq) * 1e3)
            sampler.sample()
            return deng.evaluate()

        for _ in range(n_ticks):  # stationary: must stay quiet
            false_pos += sum(1 for e in _tick(small) if e.state == "firing")
        detect_tick = None
        for i in range(n_ticks):  # hot swap to the heavier engine
            if any(e.state == "firing" for e in _tick(big)):
                detect_tick = i + 1
                break
        record["drift"] = {
            "stationary_false_positives": false_pos,
            "detected": detect_tick is not None,
            "ticks_to_detect": detect_tick,
            "fast_over_slow": None if drift._fast is None or not drift._slow
            else round(drift._fast / drift._slow, 2),
        }
        assert false_pos == 0, "drift sentinel fired on a stationary stream"
        if not smoke:
            assert detect_tick is not None, "hot-swap regression undetected"

        # ---- 5. scrape+eval overhead on router p99 ------------------------
        n_requests = 240 if smoke else 720
        rounds = 1 if smoke else 5
        n_clients = 6

        def measure(slo_on):
            kw = {}
            if slo_on:
                kw = dict(fleet_metrics=True, scrape_interval_s=0.25,
                          slos=_slos(), sentinels=_sentinels(),
                          alert_rules=_rules())
            r2 = Router(port=0, hedge=False, probe_interval_s=0.5,
                        total_deadline_s=20.0, attempt_timeout_s=8.0,
                        seed=0, **kw)
            b2 = "http://127.0.0.1:%d" % r2.start()
            for r in reps:
                r2.register(r.name, r.url)
            r2.probe_once()
            lats = []
            llock = threading.Lock()

            def cl(n):
                mine = []
                for _ in range(n):
                    tq = time.perf_counter()
                    _post(b2 + "/v1/models/m:predict", p_doc)
                    mine.append(time.perf_counter() - tq)
                with llock:
                    lats.extend(mine)

            try:
                _post(b2 + "/v1/models/m:predict", p_doc)  # warm the path
                ts = [threading.Thread(target=cl,
                                       args=(n_requests // n_clients,))
                      for _ in range(n_clients)]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join()
            finally:
                r2.stop()
            return lats

        # one discarded pass per config, then interleaved rounds gated on
        # the MEDIAN of per-round p99s (same rationale as the tracing
        # bench: drift penalizes both configs equally, one noisy round on
        # a shared host can't decide the gate)
        measure(False)
        measure(True)
        rounds_off, rounds_on = [], []
        for i in range(rounds):
            rounds_off.append(round(_p99(measure(False)), 3))
            rounds_on.append(round(_p99(measure(True)), 3))
            print("  slo overhead round %d: p99 off=%.3fms on=%.3fms"
                  % (i, rounds_off[-1], rounds_on[-1]))
        p99_off = sorted(rounds_off)[len(rounds_off) // 2]
        p99_on = sorted(rounds_on)[len(rounds_on) // 2]
        record.update({
            "p99_rounds_off": rounds_off,
            "p99_rounds_on": rounds_on,
            "p99_ms_slo_off": round(p99_off, 3),
            "p99_ms_slo_on": round(p99_on, 3),
            "overhead_pct": round(100.0 * (p99_on - p99_off) / p99_off, 2),
        })
        if not smoke:
            assert p99_on <= p99_off * 1.05, (
                "scrape+eval p99 %.3fms > 1.05x off p99 %.3fms"
                % (p99_on, p99_off)
            )
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10.0)
        if router is not None:
            router.stop()
        for r in reps:
            try:
                r.terminate()
            except Exception:
                pass
        _flags.set_flags(old_flags)
        _flightrec.reset()
        shutil.rmtree(work, ignore_errors=True)
    return record


def run_recovery_bench(smoke=False):
    """Elastic-recovery evidence pass (ISSUE 9 -> RECOVERY.json).

    Three measurements on one machine:
      1. checkpoint step stall, sync vs async, at EQUAL state size: a
         synchronous `checkpoint.save_checkpoint` stalls the step for the
         full serialize+hash+fsync; `AsyncCheckpointer.save` stalls only for
         the device->host snapshot. Acceptance: async <= 20% of sync.
      2. time-to-recover: wall time of `Supervisor.resume_or_init` on a cold
         scope (startup + manifest read + shard reassembly + overlay).
      3. steps lost to a simulated preemption at `killed_at_step` with
         `ckpt_every` checkpoint cadence, plus a bit-exactness check that
         the resumed trajectory equals the uninterrupted one.
    """
    import shutil
    import tempfile

    import jax.numpy as jnp

    import paddle_tpu.fluid as fluid
    from paddle_tpu import framework
    from paddle_tpu.executor import Scope, scope_guard
    from paddle_tpu.resilience import (
        AsyncCheckpointer, Supervisor, checkpoint as rckpt,
    )

    # --- 1. stall comparison at equal state size -------------------------
    state_mb = 8 if smoke else 64
    n_arrays = 8
    rows = (state_mb << 20) // n_arrays // (64 * 4)
    rng = np.random.RandomState(0)
    # device arrays: the async save's stall IS the device->host copy
    state = {
        "p%02d" % i: jnp.asarray(rng.randn(rows, 64).astype(np.float32))
        for i in range(n_arrays)
    }
    repeats = 3 if smoke else 5
    tmp = tempfile.mkdtemp(prefix="recovery-bench-")
    sync_ms, async_ms, commit_ms = [], [], []
    try:
        cp = AsyncCheckpointer(os.path.join(tmp, "async"), keep_last=2)
        for r in range(repeats):
            t0 = time.perf_counter()
            rckpt.save_checkpoint(os.path.join(tmp, "sync"), state, r,
                                  keep_last=2)
            sync_ms.append((time.perf_counter() - t0) * 1e3)
            t0 = time.perf_counter()
            stall = cp.save(state, r)
            async_ms.append(stall * 1e3)
            cp.wait()  # commit latency is background, measured separately
            commit_ms.append((time.perf_counter() - t0) * 1e3)
        cp.close()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    med = lambda xs: sorted(xs)[len(xs) // 2]  # noqa: E731

    # --- 2+3. preemption -> resume on a tiny supervised trainer ----------
    def _mlp():
        main, startup = framework.Program(), framework.Program()
        with fluid.unique_name.guard(), fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[8], dtype="float32")
            y = fluid.layers.data(name="y", shape=[1], dtype="float32")
            h = fluid.layers.fc(input=x, size=16, act="relu")
            p = fluid.layers.fc(input=h, size=1)
            loss = fluid.layers.mean(fluid.layers.square_error_cost(p, y))
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        return main, startup, loss

    def _feed(step):
        r = np.random.RandomState(step)
        x = r.randn(16, 8).astype(np.float32)
        return {"x": x,
                "y": np.abs(x).sum(axis=1, keepdims=True).astype(np.float32)}

    ckpt_every, killed_at, total = 5, 17, 20
    root = tempfile.mkdtemp(prefix="recovery-train-")
    try:
        def train(ckpt_root, upto, every):
            main, startup, loss = _mlp()
            with scope_guard(Scope(seed=1)):
                exe = fluid.Executor()
                sup = Supervisor(exe, ckpt_root, program=main,
                                 ckpt_every=every)
                start, _ = sup.resume_or_init(startup)
                out = {}
                with sup:
                    for s in range(start, upto):
                        (lv,) = sup.run_step(program=main, feed=_feed(s),
                                             fetch_list=[loss])
                        out[s] = float(np.asarray(lv).ravel()[0])
                    sup.checkpointer.wait()
                return out, start

        golden, _ = train(os.path.join(root, "golden"), total, 0)
        eroot = os.path.join(root, "elastic")
        train(eroot, killed_at, ckpt_every)  # "preempted" here: no final save

        main, startup, loss = _mlp()
        with scope_guard(Scope(seed=2)):
            exe = fluid.Executor()
            sup = Supervisor(exe, eroot, program=main, ckpt_every=0)
            t0 = time.perf_counter()
            resumed_step, _cursor = sup.resume_or_init(startup)
            recover_s = time.perf_counter() - t0
        cont, start = train(eroot, total, 0)
        bit_exact = all(cont[s] == golden[s] for s in range(start, total))
    finally:
        shutil.rmtree(root, ignore_errors=True)

    return {
        "metric": "elastic_recovery",
        "mode": "smoke" if smoke else "full",
        "state_mb": state_mb,
        "repeats": repeats,
        "sync_save_stall_ms": round(med(sync_ms), 2),
        "async_save_stall_ms": round(med(async_ms), 2),
        "async_commit_ms": round(med(commit_ms), 2),
        # the acceptance ratio: step-visible stall, async vs sync
        "async_stall_frac_of_sync": round(med(async_ms) / med(sync_ms), 4),
        "ckpt_every": ckpt_every,
        "killed_at_step": killed_at,
        "resumed_step": resumed_step,
        "steps_lost": killed_at - resumed_step,
        "time_to_recover_s": round(recover_s, 3),
        "resume_bit_exact": bool(bit_exact),
    }


def main():
    if len(sys.argv) > 1 and sys.argv[1] == "fleet":
        # fleet chaos soak (PR 16): 3 replica subprocesses behind the
        # health-aware Router under mixed predict/generate load — SIGKILL +
        # ack-gated rejoin mid-run, then conn_reset and slow_response rounds
        # proving the breaker opens and re-closes with zero client-visible
        # errors; writes FLEET.json next to this file ("smoke" shrinks the
        # soak, skips the tracked file)
        smoke = "smoke" in sys.argv[2:]
        rec = run_fleet_bench(smoke=smoke)
        if not smoke:
            out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "FLEET.json")
            with open(out, "w") as f:
                json.dump(rec, f, indent=1)
        print(json.dumps(rec, indent=1))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "tracing":
        # tracing + flight-recorder evidence pass (ISSUE 19): serving p99
        # with tracing on <= 1.05x off; a 3-replica chaos run (conn_reset +
        # SIGKILL) with served_fraction 1.0 whose trace shards carry one
        # failover trace across >= 3 OS processes and whose bundles show
        # the failed attempt + retry; writes TRACING.json next to this
        # file ("smoke" shrinks the run, skips the tracked file)
        smoke = "smoke" in sys.argv[2:]
        rec = run_tracing_bench(smoke=smoke)
        if not smoke:
            out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "TRACING.json")
            with open(out, "w") as f:
                json.dump(rec, f, indent=1)
        print(json.dumps(rec, indent=1))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "slo":
        # fleet SLO-engine evidence pass (ISSUE 20): exposition round-trip
        # + merged-percentile bit-equality, a steady-state round with zero
        # false alerts, a slow_response chaos round whose fast-burn latency
        # page fires < 60s and resolves after the fault clears (with the
        # slo_alert flight-recorder bundle), hot-swap drift detection, and
        # the scrape+eval overhead gate on router p99; writes SLO.json next
        # to this file ("smoke" shrinks the rounds, skips the tracked file)
        smoke = "smoke" in sys.argv[2:]
        rec = run_slo_bench(smoke=smoke)
        if not smoke:
            out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "SLO.json")
            with open(out, "w") as f:
                json.dump(rec, f, indent=1)
        print(json.dumps(rec, indent=1))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "recovery":
        # elastic-recovery evidence pass (ISSUE 9): async-checkpoint stall
        # vs synchronous save at equal state size (target <= 0.20),
        # time-to-recover, steps lost to a preemption; writes RECOVERY.json
        # next to this file ("smoke" shrinks sizes, skips the tracked file)
        smoke = "smoke" in sys.argv[2:]
        rec = run_recovery_bench(smoke=smoke)
        if not smoke:
            out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "RECOVERY.json")
            with open(out, "w") as f:
                json.dump(rec, f, indent=1)
        print(json.dumps(rec, indent=1))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "online":
        # online-learning evidence pass (ISSUE 15): streaming DeepFM trainer
        # publishing base+delta versions while a ModelServer hot-swaps them
        # under client load — zero 5xx, bounded staleness, offline bit-
        # parity; writes ONLINE.json next to this file ("smoke" shrinks the
        # run, skips the tracked file)
        smoke = "smoke" in sys.argv[2:]
        rec = run_online_bench(smoke=smoke)
        if not smoke:
            out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "ONLINE.json")
            with open(out, "w") as f:
                json.dump(rec, f, indent=1)
        print(json.dumps(rec, indent=1))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "recsys":
        # sparse-embedding-engine evidence pass (PR 8): writes
        # BENCH_recsys.json next to this file; "smoke" keeps sizes CPU-CI
        # friendly and skips the tracked-metric file
        smoke = "smoke" in sys.argv[2:]
        rec = run_recsys_bench(smoke=smoke)
        if not smoke:
            out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BENCH_recsys.json")
            with open(out, "w") as f:
                json.dump(rec, f, indent=1)
        print(json.dumps(rec, indent=1))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "reader":
        # reader-pipeline evidence pass (ISSUE 7): uncached uint8-image and
        # token paths with and without the native data runtime; "smoke"
        # keeps sizes CPU-CI friendly and skips the tracked-metric file
        smoke = "smoke" in sys.argv[2:]
        rec = run_reader_bench(smoke=smoke)
        if not smoke:
            out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BENCH_reader.json")
            with open(out, "w") as f:
                json.dump(rec, f, indent=1)
        print(json.dumps(rec))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "passes":
        # pass-framework evidence (ISSUE 10): pipeline off vs the
        # training_default preset on LeNet + tiny transformer — step time,
        # op/HLO counts, fold/DCE/fusion payloads, loss-parity delta; writes
        # PASSES.json next to this file ("smoke" shrinks steps, skips the
        # tracked file)
        smoke = "smoke" in sys.argv[2:]
        rec = run_passes_bench(smoke=smoke)
        if not smoke:
            out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "PASSES.json")
            with open(out, "w") as f:
                json.dump(rec, f, indent=1)
        print(json.dumps(rec, indent=1))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "quant":
        # quantization evidence pass (ISSUE 18): calibrated-int8 zoo top-1
        # vs fp32, v5e-roofline single-shot speedup, kv-int8 2x-slots
        # generation entry, fp8 training-matmul step time; writes QUANT.json
        # next to this file ("smoke" shrinks sizes, skips the tracked file)
        smoke = "smoke" in sys.argv[2:]
        rec = run_quant_bench(smoke=smoke)
        if not smoke:
            out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "QUANT.json")
            with open(out, "w") as f:
                json.dump(rec, f, indent=1)
        print(json.dumps(rec, indent=1))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "mfu_audit":
        # per-HLO MFU gap audit with the HBM memcpy microbench grounding the
        # memory roofline in measured bandwidth (tools/mfu_audit.py; ISSUE
        # 11 satellite). All trailing args pass through, e.g.:
        #   python bench.py mfu_audit transformer --pass-pipeline
        #   training_fused --probe
        import importlib.util as _ilu

        spec = _ilu.spec_from_file_location(
            "mfu_audit",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "tools", "mfu_audit.py"),
        )
        mod = _ilu.module_from_spec(spec)
        spec.loader.exec_module(mod)
        mod.main(sys.argv[2:])
        return
    if len(sys.argv) > 1 and sys.argv[1] == "generation":
        # autoregressive-serving evidence pass (ISSUE 12): Poisson
        # mixed-length load through the token-level continuous scheduler vs
        # the naive whole-sequence ablation; writes GENSERVE.json next to
        # this file ("smoke" shrinks the model/load, skips the tracked file)
        smoke = "smoke" in sys.argv[2:]
        rec = run_generation_bench(smoke=smoke)
        if not smoke:
            out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "GENSERVE.json")
            with open(out, "w") as f:
                json.dump(rec, f, indent=1)
        print(json.dumps(rec, indent=1))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "serving":
        # serving-runtime evidence pass (scripts/build_and_test.sh): writes
        # SERVING.json next to this file
        rec = run_serving_bench()
        out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "SERVING.json")
        with open(out, "w") as f:
            json.dump(rec, f, indent=1)
        print(json.dumps(rec, indent=1))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "sharding":
        # sharding-rule engine evidence pass (PR 13): tp x fsdp vs
        # dp-replicated — per-chip param+state bytes, step time, loss
        # parity, paper-size HBM projection; writes MULTICHIP_SHARDING.json
        # next to this file ("smoke" shrinks sizes, skips the tracked file)
        smoke = "smoke" in sys.argv[2:]
        rec = run_sharding_bench(smoke=smoke)
        if rec is None:
            raise SystemExit("sharding bench needs an 8-device mesh")
        if not smoke:
            out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "MULTICHIP_SHARDING.json")
            with open(out, "w") as f:
                json.dump(rec, f, indent=1)
        print(json.dumps(rec, indent=1))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "pp":
        # standalone pp-bubble evidence pass (scripts/build_and_test.sh):
        # writes MULTICHIP_PP.json next to this file
        rec = run_pp_bench()
        if rec is None:
            raise SystemExit("pp bench needs a dp*pp-device mesh")
        out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "MULTICHIP_PP.json")
        with open(out, "w") as f:
            json.dump(rec, f, indent=1)
        print(json.dumps(rec, indent=1))
        return
    batch_size = int(sys.argv[1]) if len(sys.argv) > 1 else 256
    ips = single_ips = pyreader_ips = pyreader_u8_ips = None
    ladder = [batch_size] + [b for b in (128, 64, 32) if b < batch_size]
    for bs in ladder:  # memory-headroom fallback: strictly smaller sizes only
        try:
            ips, single_ips, pyreader_ips, pyreader_u8_ips = run(batch_size=bs)
            break
        except Exception as e:
            print("bench fallback from bs=%d: %r" % (bs, e), file=sys.stderr)
    if ips is None:
        raise SystemExit("all batch sizes failed")
    # headline = the faster of single-dispatch and multi-step: which one
    # wins depends on the harness's per-call dispatch cost, and round 4
    # showed the unconditional multi-step headline can sit BELOW the
    # same run's single-dispatch measurement
    headline = max(ips, single_ips or 0.0)
    record = {
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(headline, 2),
        "unit": "images/sec",
        "vs_baseline": round(headline / BASELINE_IMAGES_PER_SEC, 2),
        "resnet50_multistep_images_per_sec": round(ips, 2),
    }
    if single_ips:
        # one dispatch per step vs the k-step scan (the delta IS the
        # measured per-call dispatch cost, either sign)
        record["resnet50_singledispatch_images_per_sec"] = round(single_ips, 2)
    if pyreader_ips:
        # input-pipeline evidence: PyReader-fed throughput as a fraction of
        # the staged-batch ceiling (target >=0.95 — async staging overlaps
        # the host->device transfer with compute). TUNNEL BYTE MATH: this
        # harness's host->device path moves ~22 MB/s; an f32 bs=256 image
        # batch is 154 MB -> ~7 s/step of wire time vs ~0.11 s of compute,
        # so the f32 image frac measures the tunnel, not the pipeline
        # (uint8 wire cuts it 4x; the byte-light token frac below is the
        # keep-up proof the design target speaks to).
        # denominator: the SINGLE-dispatch staged ceiling — the pyreader
        # passes run one dispatch per step, so dividing by the multi-step
        # headline would misattribute dispatch overhead to the pipeline
        denom = single_ips or ips
        record["pyreader_images_per_sec"] = round(pyreader_ips, 2)
        record["pyreader_frac"] = round(pyreader_ips / denom, 3)
    if pyreader_u8_ips:
        record["pyreader_uint8_images_per_sec"] = round(pyreader_u8_ips, 2)
        record["pyreader_frac_uint8"] = round(pyreader_u8_ips / (single_ips or ips), 3)
    try:
        # headline MFU config: bf16-stored Adam moments (f32 compute) — the
        # TPU-native training configuration (convergence-tested,
        # tests/test_ops_optimizers.py) which halves optimizer-state memory
        # and its share of the dW-fusion HBM traffic (PROFILE.md audit) —
        # under the training_fused preset (Pallas GEMM-epilogue /
        # layer_norm / multi-tensor-Adam substitution, docs/passes.md)
        mfu = run_transformer_mfu(pass_pipeline="training_fused")
        tfs = mfu["tflops_min_window"]
        record["transformer_tflops_per_sec"] = round(tfs, 1)
        record["transformer_mfu_vs_nominal_peak"] = round(tfs / NOMINAL_BF16_TFLOPS, 3)
        # estimator audit trail: the median and every window time (min far
        # below median = suspect headline; see run_transformer_mfu)
        record["transformer_tflops_median_window"] = round(
            mfu["tflops_median_window"], 1
        )
        record["transformer_window_ms_per_step"] = mfu["window_ms_per_step"]
    except Exception as e:
        print("transformer MFU pass failed: %r" % e, file=sys.stderr)
    try:
        # kernel-substitution ablation: the SAME step with the fuse_* passes
        # off — the delta against the headline is the Pallas tier's win
        mfu_unfused = run_transformer_mfu(pass_pipeline="")
        tfs_u = mfu_unfused["tflops_min_window"]
        record["transformer_tflops_unfused"] = round(tfs_u, 1)
        record["transformer_mfu_unfused"] = round(
            tfs_u / NOMINAL_BF16_TFLOPS, 3
        )
        record["transformer_unfused_window_ms_per_step"] = mfu_unfused[
            "window_ms_per_step"
        ]
    except Exception as e:
        print("unfused-ablation MFU pass failed: %r" % e, file=sys.stderr)
    try:
        # reference-comparable variant: full-f32 Adam state
        mfu_f32 = run_transformer_mfu(moment_dtype=None)
        tfs_f32 = mfu_f32["tflops_min_window"]
        record["transformer_tflops_f32_state"] = round(tfs_f32, 1)
        record["transformer_mfu_f32_state"] = round(
            tfs_f32 / NOMINAL_BF16_TFLOPS, 3
        )
        record["transformer_f32_state_window_ms_per_step"] = mfu_f32[
            "window_ms_per_step"
        ]
    except Exception as e:
        print("f32-state MFU pass failed: %r" % e, file=sys.stderr)
    try:
        # ZeRO-1 evidence (multi-device meshes only; the single-chip bench
        # harness skips): step time + per-chip optimizer-state bytes,
        # Reduce(ZeRO-1) vs AllReduce(replicated) — docs/parallelism.md
        z1 = run_zero1_bench()
        if z1:
            record.update(z1)
    except Exception as e:
        print("zero1 bench pass failed: %r" % e, file=sys.stderr)
    try:
        lstm_ms, token_frac = run_lstm(measure_pipeline=True)
        record["lstm_ms_per_batch"] = round(lstm_ms, 1)
        record["lstm_vs_baseline"] = round(BASELINE_LSTM_MS_PER_BATCH / lstm_ms, 2)
        if token_frac:
            # byte-light keep-up proof: ~51.5 KB/step token feed -> ~2.3 ms
            # wire time hidden inside ~15 ms/step compute (target >= 0.95)
            record["pyreader_frac_tokens"] = round(token_frac, 3)
    except Exception as e:
        print("lstm pass failed: %r" % e, file=sys.stderr)
    try:
        vgg_ips = run_vgg19()
        record["vgg19_images_per_sec"] = round(vgg_ips, 1)
        record["vgg19_vs_baseline"] = round(vgg_ips / BASELINE_VGG19_IMAGES_PER_SEC, 2)
    except Exception as e:
        print("vgg19 pass failed: %r" % e, file=sys.stderr)
    print(json.dumps(record))


if __name__ == "__main__":
    main()
